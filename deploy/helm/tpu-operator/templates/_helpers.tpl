{{- define "tpu-operator.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "tpu-operator.storeURL" -}}
{{- if .Values.store.url -}}
{{ .Values.store.url }}
{{- else if .Values.store.tlsSecret -}}
https://tpu-store:{{ .Values.store.port }}
{{- else -}}
http://tpu-store:{{ .Values.store.port }}
{{- end -}}
{{- end -}}

{{- /* truthy when in-chart store clients (operator, agent) must pin the
       served TLS cert as their trust root; external https store.url
       deployments bring their own CA instead. */ -}}
{{- define "tpu-operator.clientTLS" -}}
{{- if and .Values.store.tlsSecret (not .Values.store.url) -}}true{{- end -}}
{{- end -}}

{{- /* readEnabled=true makes store+agent pods mount and require
       /etc/tpujob/read-token — but with create=true the chart renders the
       Secret itself, and without readValue it has no read-token key to put
       in it: every pod would crash-loop on the missing file (fail-closed,
       but a silent values-combination footgun). Fail the RENDER instead.
       Included by every template that gates --read-token-file. */ -}}
{{- define "tpu-operator.validateReadToken" -}}
{{- if and .Values.token.readEnabled .Values.token.create (not .Values.token.readValue) -}}
{{- fail "token.readEnabled=true with token.create=true requires token.readValue (the chart-rendered Secret needs a read-token key); set token.readValue, or bring your own Secret with token.create=false" -}}
{{- end -}}
{{- end -}}
