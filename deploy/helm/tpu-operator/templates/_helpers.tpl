{{- define "tpu-operator.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "tpu-operator.storeURL" -}}
{{- if .Values.store.url -}}
{{ .Values.store.url }}
{{- else if .Values.store.tlsSecret -}}
https://tpu-store:{{ .Values.store.port }}
{{- else -}}
http://tpu-store:{{ .Values.store.port }}
{{- end -}}
{{- end -}}

{{- /* truthy when in-chart store clients (operator, agent) must pin the
       served TLS cert as their trust root; external https store.url
       deployments bring their own CA instead. */ -}}
{{- define "tpu-operator.clientTLS" -}}
{{- if and .Values.store.tlsSecret (not .Values.store.url) -}}true{{- end -}}
{{- end -}}
