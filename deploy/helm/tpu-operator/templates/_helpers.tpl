{{- define "tpu-operator.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "tpu-operator.storeURL" -}}
{{- if .Values.store.url -}}
{{ .Values.store.url }}
{{- else -}}
http://tpu-store:{{ .Values.store.port }}
{{- end -}}
{{- end -}}
