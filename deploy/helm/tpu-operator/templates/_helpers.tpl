{{- define "tpu-operator.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end -}}

{{- define "tpu-operator.storeURL" -}}
http://tpu-store:{{ .Values.store.port }}
{{- end -}}
