# Operator image. ≙ /root/reference/Dockerfile:1-14 (two-stage distroless Go
# build selecting a controller binary); here stage 1 compiles the native
# collective library and stage 2 is a slim Python runtime carrying the
# operator package, the compiled libtpucoll, and the deploy schema.
#
#   docker build -t tpu-operator .
#   docker run tpu-operator --store sqlite:/data/store.db --executor local

FROM python:3.12-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY native/ native/
RUN make -C native

FROM python:3.12-slim
RUN pip install --no-cache-dir pyyaml \
    # jax/flax/optax/orbax are workload deps: bake the TPU wheel matching the
    # target fleet here (kept out of the base image on purpose — the operator
    # itself only needs the stdlib + yaml)
    && true
WORKDIR /app
COPY mpi_operator_tpu/ mpi_operator_tpu/
COPY examples/ examples/
COPY deploy/tpujob-schema.json deploy/tpujob-schema.json
COPY --from=build /src/native/build/libtpucoll.so native/build/libtpucoll.so
COPY --from=build /src/native/build/pi native/build/pi
ENTRYPOINT ["python", "-m", "mpi_operator_tpu.opshell"]
CMD ["--monitoring-port", "8080"]
