"""Control-plane benchmark: reconcile storm against the sqlite-backed HTTP
store, with and without the informer cache (machinery/cache.py).

The metric the informer/lister subsystem exists to move: before it, every
reconcile issued full ``store.list``/``get`` round-trips — over HTTP in the
distributed deployment — so store read load scaled as
O(jobs × pods × resyncs). With listers, steady-state controller reads come
from the watch-fed cache and the store sees only writes plus one long-poll.

Shape: N synthetic TPUJobs × M workers each (default 200 × 8 — the ISSUE 1
acceptance point) are created through a real HttpStoreClient against a real
StoreServer backed by SqliteStore. The controller converges them (service,
configmap, podgroup, workers, status), the gang scheduler binds every gang,
and then a steady-state storm re-reconciles every job for R rounds while
measuring per-sync latency and the server's read counters. Run it via::

  python bench_controlplane.py                      # both modes + compare
  BENCH_MODEL=controlplane python bench.py          # same, no TPU work

Knobs: BENCH_CP_JOBS, BENCH_CP_PODS, BENCH_CP_ROUNDS, BENCH_CP_MODES
("store", "informer", "write", "replica", "hist", "traceoverhead",
"scale", "serve", "fanout", "slo", or a comma list). No jax required —
this is the pure-python control plane. The **slo** mode (ISSUE 13) is
the alerting plane's acceptance run: a seeded store-latency fault must
fire the matching burn-rate alert within its documented detection bound,
clear after heal, dump a flight-recorder bundle `ctl trace
--last-incident` renders rc=0, and the monitor's scrape tax must stay
≤2% of reconcile p50 — detection run TWICE on one seed. The **scale** mode (ISSUE 10) drives a
hollow-node fleet (BENCH_CP_SCALE_NODES × simulated nodes,
BENCH_CP_SCALE_JOBS jobs) against the sharded+fair-queued stack and reads
p50/p99 out of the PR 9 histograms with p99 SLOs as the tripwire;
**fanout** proves watch fan-out encode cost is O(events), not
O(watchers×events). The **serve** mode (ISSUE 11) runs the serving
workload class on a hollow fleet: a diurnal+spike offered-load curve
against an autoscaled TPUServe sharing the cluster with a batch backlog —
asserting the autoscaler tracks the curve (≥4× spike, scale-to-zero), a
mid-run rolling update opens zero unready windows, serve-readiness p99
meets its SLO, and the batch backlog still completes via
preempt-then-free-restart (visible in `ctl trace`).
The **hist** mode proves the exported latency histograms (ISSUE 9) agree
with the direct timers within bucket resolution; **traceoverhead** bounds
the tracing tax (reconcile p50 traced vs untraced, acceptance ≤5%).

The **write mode** (BENCH_CP_MODES=write) measures the write-path twin of
the informer work: status updates as server-side merge-patch (1 request)
vs the GET+PUT optimistic loop (2+), simulated agent ticks (Node heartbeat
+ dirty pod mirrors) as one patch-batch vs per-object round-trips —
O(pods) → O(1) — plus the idle-writes-are-zero check, at 200 jobs × 8
pods with BENCH_CP_AGENTS (default 16) simulated agents churning.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mpi_operator_tpu.api.types import (  # noqa: E402
    Container,
    ObjectMeta,
    PodTemplate,
    ReplicaSpec,
    RunPolicy,
    SliceSpec,
    TPUJob,
    TPUJobSpec,
)
from mpi_operator_tpu.controller.controller import (  # noqa: E402
    ControllerOptions,
    TPUJobController,
)
from mpi_operator_tpu.machinery.cache import InformerCache  # noqa: E402
from mpi_operator_tpu.machinery.events import EventRecorder  # noqa: E402
from mpi_operator_tpu.machinery.http_store import (  # noqa: E402
    HttpStoreClient,
    StoreServer,
)
from mpi_operator_tpu.machinery.sqlite_store import SqliteStore  # noqa: E402
from mpi_operator_tpu.scheduler.gang import GangScheduler  # noqa: E402


def _make_job(i: int, pods: int, clean: str = "None") -> TPUJob:
    return TPUJob(
        metadata=ObjectMeta(name=f"storm-{i:04d}", namespace="bench"),
        spec=TPUJobSpec(
            slots_per_worker=1,
            run_policy=RunPolicy(clean_pod_policy=clean),
            worker=ReplicaSpec(
                replicas=pods,
                restart_policy="Never",
                template=PodTemplate(
                    container=Container(image="bench/noop", command=["true"])
                ),
            ),
            slice=SliceSpec(accelerator="cpu", chips_per_host=1),
        ),
    )


def _reads(stats: dict) -> int:
    """Store-side read requests: object gets + lists. Watch long-polls are
    reported separately — they are the informer's O(1) replacement, not the
    per-reconcile load this benchmark measures."""
    return stats.get("get", 0) + stats.get("list", 0)


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _slo_ms(name: str, scale: float = 1.0) -> float:
    """A p99 tripwire threshold in ms from THE SLO config file
    (controller/slo_defaults.json or $TPUJOB_SLO_CONFIG) — the same file
    the runtime burn-rate monitor evaluates, so bench and monitor can
    never disagree on an objective. The entry's env knob (e.g.
    BENCH_CP_SLO_RECONCILE_P99_MS) still overrides, ABSOLUTE (it beats
    ``scale`` — a deployment that exported a bound meant exactly it)."""
    from mpi_operator_tpu.controller.slo_monitor import load_slo_config

    return load_slo_config().threshold_ms(name, scale=scale)


def run_mode(mode: str, jobs: int, pods: int, rounds: int) -> dict:
    """One full converge + storm in ``mode`` ('store' = direct reads,
    'informer' = lister reads) against a fresh sqlite-backed HTTP store."""
    tmp = tempfile.mkdtemp(prefix=f"bench-cp-{mode}-")
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0).start()
    client = HttpStoreClient(server.url, timeout=30.0, watch_poll_timeout=5.0)
    cache = None
    try:
        if mode == "informer":
            cache = InformerCache(client).start()
            if not cache.wait_for_sync(30.0):
                raise RuntimeError("informer cache never synced")
        recorder = EventRecorder(client)
        controller = TPUJobController(
            client, recorder, ControllerOptions(threadiness=0), cache=cache
        )
        scheduler = GangScheduler(client, recorder, cache=cache)

        keys = []
        for i in range(jobs):
            job = client.create(_make_job(i, pods))
            keys.append(job.metadata.key())

        # converge: drive sync_handler + scheduler.sync directly (no worker
        # threads — deterministic measurement) until a full pass of syncs
        # succeeds twice; informer mode needs the watch to carry each pass's
        # writes back into the cache before the next pass settles
        t_conv = time.perf_counter()
        clean_passes = 0
        for _ in range(30):
            ok = all([controller.sync_handler(k) for k in keys])
            scheduler.sync()
            clean_passes = clean_passes + 1 if ok else 0
            if clean_passes >= 2:
                break
            if cache is not None:
                time.sleep(0.3)  # let the watch land this pass's writes
        converge_s = time.perf_counter() - t_conv
        if cache is not None:
            time.sleep(0.5)  # quiesce: cache observes the final writes

        # steady-state storm: every job re-reconciled, rounds times over
        stats0 = server.stats()
        lat = []
        t0 = time.perf_counter()
        for _ in range(rounds):
            for k in keys:
                t = time.perf_counter()
                controller.sync_handler(k)
                lat.append(time.perf_counter() - t)
            scheduler.sync()
        elapsed = time.perf_counter() - t0
        stats1 = server.stats()

        lat.sort()
        reads = _reads(stats1) - _reads(stats0)
        writes = _writes(stats1) - _writes(stats0)
        return {
            "metric": "controlplane_reconcile",
            "mode": mode,
            "jobs": jobs,
            "pods_per_job": pods,
            "rounds": rounds,
            "syncs": len(lat),
            "sync_p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "sync_p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            "store_read_qps": round(reads / elapsed, 1),
            "store_reads_per_sync": round(reads / max(1, len(lat)), 2),
            "store_writes": writes,
            "watch_polls": stats1.get("watch", 0) - stats0.get("watch", 0),
            "storm_elapsed_s": round(elapsed, 2),
            "converge_s": round(converge_s, 2),
        }
    finally:
        if cache is not None:
            cache.stop()
        client.close()
        server.stop()
        backing.close()


def _writes(stats: dict) -> int:
    """Store-side write requests (patch_batch counts as ONE request — that
    collapse is the point; its per-item patches are server-internal)."""
    return sum(stats.get(w, 0) for w in ("create", "update", "delete",
                                         "patch", "patch_batch"))


def run_write_mode(jobs: int, pods: int, agents: int) -> dict:
    """The write-path benchmark: converge the cluster once (informer reads,
    patch writes), then measure

    - **status update**: old GET+PUT optimistic loop vs status-subresource
      PATCH, p50/p99 and store requests per update;
    - **agent tick**: old per-object round-trips (Node GET+PUT + per-dirty-
      pod GET+PUT) vs ONE patch-batch, requests per tick;
    - **agent churn**: ``agents`` threads ticking concurrently with a
      job's worth of dirty mirrors each, both write paths, wall + QPS +
      server-bounced conflicts;
    - **idle**: after everything drains, a 5s window must show ZERO writes
      (the elision guarantee, mirroring the zero-read one).
    """
    import threading

    from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE, Node

    tmp = tempfile.mkdtemp(prefix="bench-cp-write-")
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0).start()
    client = HttpStoreClient(server.url, timeout=30.0, watch_poll_timeout=5.0)
    cache = InformerCache(client).start()
    try:
        if not cache.wait_for_sync(30.0):
            raise RuntimeError("informer cache never synced")
        recorder = EventRecorder(client)
        controller = TPUJobController(
            client, recorder, ControllerOptions(threadiness=0), cache=cache
        )
        scheduler = GangScheduler(client, recorder, cache=cache)
        keys = []
        for i in range(jobs):
            job = client.create(_make_job(i, pods))
            keys.append(job.metadata.key())
        stats0 = server.stats()
        clean = 0
        for _ in range(30):
            ok = all([controller.sync_handler(k) for k in keys])
            scheduler.sync()
            clean = clean + 1 if ok else 0
            if clean >= 2:
                break
            time.sleep(0.3)
        time.sleep(0.5)
        stats_conv = server.stats()
        converge_writes = _writes(stats_conv) - _writes(stats0)

        all_pods = client.list("Pod", "bench")
        # ---- status update: GET+PUT loop vs one PATCH --------------------
        n_updates = min(400, len(all_pods))
        s0 = server.stats()
        put_lat = []
        for i, p in enumerate(all_pods[:n_updates]):
            t = time.perf_counter()
            cur = client.get("Pod", p.metadata.namespace, p.metadata.name)
            cur.status.message = f"put {i}"
            client.update(cur)
            put_lat.append(time.perf_counter() - t)
        s1 = server.stats()
        patch_lat = []
        for i, p in enumerate(all_pods[:n_updates]):
            t = time.perf_counter()
            client.patch(
                "Pod", p.metadata.namespace, p.metadata.name,
                {"status": {"message": f"patch {i}"}}, subresource="status",
            )
            patch_lat.append(time.perf_counter() - t)
        s2 = server.stats()
        put_req = (_reads(s1) - _reads(s0)) + (_writes(s1) - _writes(s0))
        patch_req = (_reads(s2) - _reads(s1)) + (_writes(s2) - _writes(s1))
        put_lat.sort()
        patch_lat.sort()

        # ---- agent ticks: per-object round-trips vs one patch-batch ------
        for a in range(agents):
            node = Node()
            node.metadata.namespace = NODE_NAMESPACE
            node.metadata.name = f"bench-agent-{a:02d}"
            node.status.ready = True
            node.status.last_heartbeat = time.time()
            client.try_get("Node", NODE_NAMESPACE, node.metadata.name) \
                or client.create(node)
        shard = [all_pods[a::agents] for a in range(agents)]

        def old_tick(cl, a: int, dirty: list) -> None:
            cur = cl.get("Node", NODE_NAMESPACE, f"bench-agent-{a:02d}")
            cur.status.last_heartbeat = time.time()
            cl.update(cur)
            for p in dirty:
                cp = cl.get("Pod", p.metadata.namespace, p.metadata.name)
                cp.status.message = "old-tick"
                cl.update(cp)

        def new_tick(cl, a: int, dirty: list) -> None:
            items = [{
                "kind": "Node", "namespace": NODE_NAMESPACE,
                "name": f"bench-agent-{a:02d}", "subresource": "status",
                "patch": {"status": {"last_heartbeat": time.time()}},
            }]
            items += [{
                "kind": "Pod", "namespace": p.metadata.namespace,
                "name": p.metadata.name, "subresource": "status",
                "patch": {"status": {"message": "new-tick"}},
            } for p in dirty]
            cl.patch_batch(items)

        dirty_per_tick = pods  # a job's worth of mirrors lands each tick
        s0 = server.stats()
        old_tick(client, 0, shard[0][:dirty_per_tick])
        s1 = server.stats()
        new_tick(client, 0, shard[0][:dirty_per_tick])
        s2 = server.stats()
        tick_req_old = (_reads(s1) - _reads(s0)) + (_writes(s1) - _writes(s0))
        tick_req_new = (_reads(s2) - _reads(s1)) + (_writes(s2) - _writes(s1))

        churn = {}
        ticks = 20
        for label, tick in (("old", old_tick), ("new", new_tick)):
            clients = [
                HttpStoreClient(server.url, timeout=30.0,
                                watch_poll_timeout=5.0)
                for _ in range(agents)
            ]
            s0 = server.stats()
            t0 = time.perf_counter()

            def run_agent(a, cl):
                for _ in range(ticks):
                    tick(cl, a, shard[a][:dirty_per_tick])

            threads = [
                threading.Thread(target=run_agent, args=(a, cl))
                for a, cl in enumerate(clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            s1 = server.stats()
            req = (_reads(s1) - _reads(s0)) + (_writes(s1) - _writes(s0))
            churn[label] = {
                "elapsed_s": round(elapsed, 2),
                "requests": req,
                "requests_per_tick": round(req / (agents * ticks), 2),
                "store_qps": round(req / elapsed, 1),
                "conflicts": s1.get("conflict", 0) - s0.get("conflict", 0),
            }
            for cl in clients:
                cl.close()

        # ---- idle: the elision guarantee ---------------------------------
        for _ in range(2):  # settle reconciles of everything above
            for k in keys:
                controller.sync_handler(k)
            scheduler.sync()
            time.sleep(0.3)
        s0 = server.stats()
        time.sleep(5.0)
        for k in keys:
            controller.sync_handler(k)  # a full reconcile pass, all no-ops
        scheduler.sync()
        s1 = server.stats()
        idle_writes = _writes(s1) - _writes(s0)

        return {
            "metric": "controlplane_write_path",
            "jobs": jobs,
            "pods_per_job": pods,
            "agents": agents,
            "converge_writes_per_job": round(converge_writes / jobs, 2),
            "status_put_p50_ms": round(_percentile(put_lat, 0.50) * 1e3, 3),
            "status_put_p99_ms": round(_percentile(put_lat, 0.99) * 1e3, 3),
            "status_put_requests_per_update": round(put_req / n_updates, 2),
            "status_patch_p50_ms": round(_percentile(patch_lat, 0.50) * 1e3, 3),
            "status_patch_p99_ms": round(_percentile(patch_lat, 0.99) * 1e3, 3),
            "status_patch_requests_per_update": round(
                patch_req / n_updates, 2),
            "agent_tick_requests_old": tick_req_old,
            "agent_tick_requests_new": tick_req_new,
            "churn_ticks_per_agent": ticks,
            "churn_dirty_pods_per_tick": dirty_per_tick,
            "churn_old": churn["old"],
            "churn_new": churn["new"],
            "idle_writes": idle_writes,
        }
    finally:
        cache.stop()
        client.close()
        server.stop()
        backing.close()


def run_hist_mode(writes: int) -> dict:
    """The histogram read-back check (BENCH_CP_MODES=hist, run it
    standalone so the exported counts are this workload's): drive the
    write path (status-subresource PATCHes — the PERF round 7 workload),
    then read p50/p99 BACK OUT of the /metrics-exported
    ``tpu_operator_store_request_latency_seconds`` histogram via the
    strict exposition parser, and check they agree with the direct
    perf_counter timers within one bucket step. This is the acceptance
    proof that the numbers PERF.md claims are the numbers a Prometheus
    scraping /metrics would compute."""
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.opshell import metrics

    tmp = tempfile.mkdtemp(prefix="bench-cp-hist-")
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0).start()
    client = HttpStoreClient(server.url, timeout=30.0, watch_poll_timeout=5.0)
    try:
        for i in range(writes):
            client.create(Pod(metadata=ObjectMeta(
                name=f"h-{i:05d}", namespace="bench")))
        before = metrics.store_request_latency.count(
            verb="patch", backend="SqliteStore")
        lat = []
        for i in range(writes):
            t = time.perf_counter()
            client.patch(
                "Pod", "bench", f"h-{i:05d}",
                {"status": {"message": f"hist {i}"}}, subresource="status",
            )
            lat.append(time.perf_counter() - t)
        lat.sort()
        # (a) the agreement proof: the SAME client-observed latencies PERF
        # measures, pushed through a histogram with the standard buckets,
        # rendered to exposition text, strict-parsed back, and quantiled —
        # direct timer vs histogram read-back must agree within one bucket
        # step (the histogram's resolution limit)
        client_hist = metrics._Histogram(
            "bench_client_patch_latency_seconds",
            "client-observed status-patch latency (the PERF write-path "
            "measurement point)",
        )
        for v in lat:
            client_hist.observe(v)
        client_text = client_hist.render() + "\n"
        # (b) the deployment view: what a Prometheus scraping /metrics
        # computes from the server-side verb×backend histogram (handler
        # time — the client−server delta is the loopback HTTP cost)
        text = metrics.REGISTRY.render()
        metrics.parse_exposition(text)  # the endpoint must stay machine-valid
        out = {
            "metric": "controlplane_histogram_readback",
            "writes": writes,
            "hist_observations": metrics.store_request_latency.count(
                verb="patch", backend="SqliteStore") - before,
        }
        buckets = (0.0, *client_hist.buckets, float("inf"))
        for q, name in ((0.50, "p50"), (0.99, "p99")):
            direct = _percentile(lat, q)
            hist = metrics.exposition_quantile(
                client_text, "bench_client_patch_latency_seconds", q)
            server_hist = metrics.exposition_quantile(
                text, "tpu_operator_store_request_latency_seconds", q,
                verb="patch", backend="SqliteStore",
            )
            i = max(1, min(len(buckets) - 2,
                           next(k for k, b in enumerate(buckets)
                                if direct <= b)))
            lo, hi = buckets[i - 1], buckets[min(len(buckets) - 1, i + 1)]
            out[f"direct_{name}_ms"] = round(direct * 1e3, 3)
            out[f"hist_{name}_ms"] = round(hist * 1e3, 3)
            out[f"server_hist_{name}_ms"] = round(server_hist * 1e3, 3)
            out[f"{name}_agrees_within_bucket"] = bool(lo <= hist <= hi)
        return out
    finally:
        client.close()
        server.stop()
        backing.close()


def run_trace_overhead(jobs: int, pods: int, rounds: int) -> dict:
    """The tracing-tax bound (BENCH_CP_MODES=traceoverhead): INTERLEAVED
    off/on/off/on informer reconcile storms (spans exported to JSONL like
    a real deployment), best-of-two per mode so run-to-run drift (sqlite
    file aging, allocator warm-up — easily ±15% between back-to-back
    storms) cancels out of the comparison; reported as a p50 regression
    percentage. Acceptance (ISSUE 9): ≤5%."""
    import shutil

    from mpi_operator_tpu.machinery import trace as tr

    d = tempfile.mkdtemp(prefix="bench-cp-traces-")
    results = {"off": [], "on": []}
    try:
        for _ in range(2):
            tr.TRACER.disable()
            results["off"].append(run_mode("informer", jobs, pods, rounds))
            tr.configure("bench", dir=d)
            results["on"].append(run_mode("informer", jobs, pods, rounds))
    finally:
        tr.TRACER.disable()
    spans = len(tr.load_spans(d))
    shutil.rmtree(d, ignore_errors=True)
    off = min(results["off"], key=lambda r: r["sync_p50_ms"])
    on = min(results["on"], key=lambda r: r["sync_p50_ms"])
    p50_off, p50_on = off["sync_p50_ms"], on["sync_p50_ms"]
    return {
        "metric": "controlplane_trace_overhead",
        "jobs": jobs,
        "pods_per_job": pods,
        "rounds": rounds,
        "runs_per_mode": 2,
        "sync_p50_ms_traced_off": p50_off,
        "sync_p50_ms_traced_on": p50_on,
        "sync_p99_ms_traced_off": off["sync_p99_ms"],
        "sync_p99_ms_traced_on": on["sync_p99_ms"],
        "p50_regression_pct": round(
            (p50_on - p50_off) / max(1e-9, p50_off) * 100.0, 1),
        "spans_exported": spans,
    }


def run_replica_mode(writes: int) -> dict:
    """The HA cost as a number (BENCH_CP_MODES=replica): write p50/p99
    at replication factor 1 (single node, no shipping) vs 3 (leased
    leader + synchronous majority log-shipping), plus the
    failover-to-first-successful-write time — SIGKILL the leader under
    auto-failover and measure until a write acks on the new one."""
    import shutil

    from mpi_operator_tpu.api.types import ObjectMeta as _Meta
    from mpi_operator_tpu.machinery.objects import Pod as _Pod
    from mpi_operator_tpu.machinery.replicated_store import ReplicaSet

    def _pod(name):
        return _Pod(metadata=_Meta(name=name, namespace="bench"))

    out: dict = {"metric": "controlplane_replica", "writes": writes}
    for rf in (1, 3):
        tmp = tempfile.mkdtemp(prefix=f"bench-replica-rf{rf}-")
        rs = ReplicaSet(rf, dir=tmp)
        try:
            assert rs.elect("n0")
            client = rs.client()
            lat = []
            for i in range(writes):
                t = time.perf_counter()
                client.create(_pod(f"w-{i:05d}"))
                lat.append(time.perf_counter() - t)
            for i in range(writes):
                t = time.perf_counter()
                client.patch(
                    "Pod", "bench", f"w-{i:05d}",
                    {"status": {"message": "bench"}}, subresource="status",
                )
                lat.append(time.perf_counter() - t)
            lat.sort()
            out[f"rf{rf}_write_p50_ms"] = round(
                _percentile(lat, 0.50) * 1e3, 3)
            out[f"rf{rf}_write_p99_ms"] = round(
                _percentile(lat, 0.99) * 1e3, 3)
        finally:
            rs.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    out["rf3_over_rf1_p50"] = round(
        out["rf3_write_p50_ms"] / max(1e-9, out["rf1_write_p50_ms"]), 2)

    # failover: kill the leader mid-traffic, clock until the first write
    # acks on the new leader (median of 3 trials)
    trials = []
    for trial in range(3):
        tmp = tempfile.mkdtemp(prefix="bench-replica-failover-")
        rs = ReplicaSet(3, dir=tmp, lease_duration=0.5, retry_period=0.05,
                        seed=trial)
        try:
            assert rs.elect("n0")
            rs.start()
            client = rs.client()
            client._attempts = 64
            client.create(_pod("pre-failover"))
            rs.crash("n0")
            t0 = time.perf_counter()
            client.create(_pod(f"post-failover-{trial}"))
            trials.append(time.perf_counter() - t0)
        finally:
            rs.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    out["failover_first_write_ms"] = round(
        sorted(trials)[len(trials) // 2] * 1e3, 1)
    out["failover_trials_ms"] = [round(t * 1e3, 1) for t in trials]
    out["lease_duration_s"] = 0.5
    return out


def _hist_quantile_delta(hist, q, before, after, **labels):
    """Quantile of a histogram's observations BETWEEN two snapshots
    (cumulative (le,count) pairs from _Histogram.snapshot) — isolates this
    bench run from whatever the process observed earlier."""
    from mpi_operator_tpu.opshell.metrics import histogram_quantile

    b = dict(before)
    delta = [(le, c - b.get(le, 0)) for le, c in after]
    return histogram_quantile(q, delta)


def run_scale_mode(nodes: int, jobs: int, pods: int) -> dict:
    """The 10k-job scale run (BENCH_CP_MODES=scale), in the DEPLOYED
    three-process shape: a sqlite-backed `tpu-store` server process
    (preencoded watch fan-out + APF fair queuing on), a hollow-fleet
    process simulating ``nodes`` agents, and THIS process as the leader —
    informer cache, sharded-workqueue controller, gang scheduler.
    (A single shared process understates the result badly: at 1k nodes
    the three planes' GIL contention dominates every latency.) ``jobs``
    TPUJobs × ``pods`` workers are submitted with wave backpressure and
    driven to Succeeded; reconcile/bind/watch-lag p50/p99 come OUT OF
    THE PR 9 HISTOGRAMS (the numbers /metrics would export), and the
    p99 SLOs are the tripwire this bench exists to arm."""
    import math
    import socket
    import subprocess
    import threading

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.opshell import metrics

    run_s = float(os.environ.get("BENCH_CP_SCALE_RUN_S", "0.2"))
    wave = int(os.environ.get("BENCH_CP_SCALE_WAVE", "500"))
    threadiness = int(os.environ.get("BENCH_CP_SCALE_WORKERS", "8"))
    # p99 SLO tripwires from the ONE config file the runtime monitor
    # evaluates (controller/slo_defaults.json; calibrated on this
    # sandbox's round-10 run — 570 / 225 / 4404 ms at 1k nodes / 10k
    # jobs — with ~2× headroom). A regression that blows these is a
    # scalability bug, not noise. Env overrides preserved per entry.
    slo_reconcile = _slo_ms("reconcile-latency")
    slo_bind = _slo_ms("scheduler-bind")
    slo_lag = _slo_ms("watch-lag")
    chips = max(2, math.ceil(jobs * pods / max(1, nodes)) + 2)

    tmp = tempfile.mkdtemp(prefix="bench-cp-scale-")
    with socket.socket() as s:  # free port for the store process
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.abspath(__file__)))
    store_proc = subprocess.Popen(
        [sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
         "--store", f"sqlite:{os.path.join(tmp, 'store.db')}",
         "--listen", f"127.0.0.1:{port}", "--log-capacity", "65536",
         "--fair-queue", "inflight=32,queue=512,max_wait=60"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    fleet_proc = None
    client = HttpStoreClient(url, timeout=60.0, watch_poll_timeout=5.0,
                             conn_refused_retries=20)
    cache = None
    controller = None
    stop = threading.Event()
    snaps = {
        "reconcile": metrics.reconcile_latency.snapshot(),
        "bind": metrics.scheduler_bind_latency.snapshot(),
        "lag": metrics.watch_delivery_lag.snapshot(),
    }
    try:
        deadline = time.time() + 30
        while time.time() < deadline:  # store process up?
            try:
                client.list("Node")
                break
            except Exception:
                time.sleep(0.2)
        fleet_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.executor.hollow",
             "--store", url, "--nodes", str(nodes),
             "--chips", str(chips), "--run-s", str(run_s),
             "--heartbeat", "15", "--seed", "10"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        cache = InformerCache(client).start()
        if not cache.wait_for_sync(30.0):
            raise RuntimeError("informer cache never synced")
        recorder = EventRecorder(client)
        controller = TPUJobController(
            client, recorder,
            ControllerOptions(threadiness=threadiness,
                              queue_shards=threadiness),
            cache=cache,
        )
        scheduler = GangScheduler(client, recorder, cache=cache)
        # O(1)-per-event progress probe off the informer stream (listing
        # 10k cached jobs per poll would make the BENCH the noisy
        # tenant); Succeeded is terminal write-once, so a name set is
        # exact
        done_names = set()

        def note_done(etype, obj):
            if obj.kind == "TPUJob" and cond.is_succeeded(obj.status):
                done_names.add(obj.metadata.name)

        cache.add_event_handler(note_done)
        # fleet registration visible before the first gangs admit
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(cache.list("Node")) >= nodes:
                break
            time.sleep(0.2)
        controller.run()

        def sched_loop():
            while not stop.is_set():
                try:
                    scheduler.sync()
                except Exception:
                    pass  # transient conflicts; next pass heals
                stop.wait(0.2)

        st = threading.Thread(target=sched_loop, daemon=True)
        st.start()

        t0 = time.perf_counter()
        submitted = 0
        done = 0
        deadline = time.time() + float(os.environ.get(
            "BENCH_CP_SCALE_DEADLINE_S", max(600.0, jobs * 0.25)))
        while time.time() < deadline:
            done = len(done_names)
            while submitted < jobs and submitted - done < wave:
                # CleanPodPolicy=All (the batch-workload default): a
                # finished job's pods/podgroup are reaped, so the
                # scheduler's per-pass working set stays O(in-flight),
                # not O(all jobs ever) — at 10k jobs the difference
                # between a ~1.5k-object and a ~30k-object deepcopy per
                # 0.2s pass in the leader process
                client.create(_make_job(submitted, pods, clean="All"))
                submitted += 1
            if done >= jobs:
                break
            time.sleep(0.5)
        elapsed = time.perf_counter() - t0
        # authoritative final count (one full list, off the clock)
        done = sum(1 for j in cache.list("TPUJob", "bench")
                   if cond.is_succeeded(j.status))
        out = {
            "metric": "controlplane_scale",
            "processes": "store / hollow-fleet / operator (deployed shape)",
            "nodes": nodes,
            "jobs": jobs,
            "pods_per_job": pods,
            "hollow_run_s": run_s,
            "jobs_succeeded": done,
            "elapsed_s": round(elapsed, 1),
            "jobs_per_s": round(done / max(1e-9, elapsed), 1),
            "queue_shards": threadiness,
        }
        for q, tag in ((0.50, "p50"), (0.99, "p99")):
            out[f"reconcile_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.reconcile_latency, q, snaps["reconcile"],
                metrics.reconcile_latency.snapshot()) * 1e3, 2)
            out[f"bind_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.scheduler_bind_latency, q, snaps["bind"],
                metrics.scheduler_bind_latency.snapshot()) * 1e3, 2)
            out[f"watch_lag_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.watch_delivery_lag, q, snaps["lag"],
                metrics.watch_delivery_lag.snapshot()) * 1e3, 2)
        out["slo"] = {
            "reconcile_p99_ms": slo_reconcile,
            "bind_p99_ms": slo_bind,
            "watch_lag_p99_ms": slo_lag,
        }
        out["slo_ok"] = bool(
            done >= jobs
            and out["reconcile_p99_ms"] <= slo_reconcile
            and out["bind_p99_ms"] <= slo_bind
            and out["watch_lag_p99_ms"] <= slo_lag
        )
        return out
    finally:
        stop.set()
        if controller is not None:
            controller.stop()
        if cache is not None:
            cache.stop()
        client.close()
        for proc in (fleet_proc, store_proc):
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def run_torture_mode(nodes: int, jobs: int, pods: int, seed: int) -> dict:
    """The fleet×chaos torture run (BENCH_CP_MODES=torture, ISSUE 12):
    the FULLY deployed shape — three wire-replicated `tpu-store` replica
    processes (peer RPCs through chaos proxies), a real `tpu-operator`
    process (controller + gang scheduler + node monitor over the
    multi-endpoint client), and a hollow fleet process (≥100 nodes /
    ≥500 jobs) — while a seeded chaos script partitions the leader from
    a follower and then SIGKILLs the leader mid-run. The bar: NO acked
    write lost at its exact rv, every job Succeeded post-failover, the
    scale-mode p99 SLO tripwires green (read from the operator's real
    /metrics exposition), and ONE connected trace spanning a pre-kill
    write → its replication ship → the winning election → a
    post-failover reconcile (`ctl trace --last-incident` renders it
    rc=0). The caller runs this TWICE on one seed (determinism)."""
    import math
    import shutil
    import signal as _signal
    import subprocess
    import threading
    import urllib.request

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.machinery.chaos import (
        ChaosController,
        ChaosProxy,
        ChaosScript,
        NamedProxyFabric,
    )
    from mpi_operator_tpu.machinery.objects import ConfigMap
    from mpi_operator_tpu.machinery.store import AlreadyExists
    from mpi_operator_tpu.machinery.replica_wire import (
        free_ports,
        wait_for_wire_leader,
    )
    from mpi_operator_tpu.api.types import ObjectMeta as _Meta
    from mpi_operator_tpu.opshell.metrics import exposition_quantile

    run_s = float(os.environ.get("BENCH_CP_TORTURE_RUN_S", "0.2"))
    wave = int(os.environ.get("BENCH_CP_TORTURE_WAVE", "200"))
    threadiness = int(os.environ.get("BENCH_CP_SCALE_WORKERS", "4"))
    # tripwires from THE SLO config file (same source as the runtime
    # monitor + scale mode). The reconcile bar is 2× the config's: a
    # DELIBERATE leader SIGKILL puts the ~2-lease failover window's
    # reconciles into p99 by design — the bar is that the window stays
    # bounded (sub-2s), not that chaos is free (measured 955 ms at
    # 100×500 with one kill). An env override stays absolute.
    slo_reconcile = _slo_ms("reconcile-latency", scale=2.0)
    slo_bind = _slo_ms("scheduler-bind")
    slo_lag = _slo_ms("watch-lag")
    chips = max(2, math.ceil(jobs * pods / max(1, nodes)) + 2)

    tmp = tempfile.mkdtemp(prefix="bench-cp-torture-")
    trace_dir = os.path.join(tmp, "traces")
    os.makedirs(trace_dir, exist_ok=True)
    ids = ["n0", "n1", "n2"]
    # one reservation pass holding every socket open (replica_wire owns
    # the collision-safe allocator): sequential bind/close pairs can be
    # handed the same ephemeral port twice
    allocated = free_ports(4)
    ports = dict(zip(ids, allocated))
    mport = allocated[3]
    direct = {nid: f"http://127.0.0.1:{ports[nid]}" for nid in ids}
    tok_path = os.path.join(tmp, "peer.token")
    with open(tok_path, "w") as f:
        f.write("torture-peer-secret\n")
    # per-directed-pair proxies carry the PEER traffic so the scripted
    # partition has a fabric to cut; client traffic dials direct. The
    # bench process stays LIGHT (proxies + chaos + probes only) — the
    # operator is its own real process, so proxy forwarding latency is
    # not coupled to reconcile work.
    proxies = {
        f"{a}->{b}": ChaosProxy(direct[b], seed=seed).start()
        for a in ids for b in ids if a != b
    }
    fabric = NamedProxyFabric(proxies)
    advertise = ",".join(f"{nid}={direct[nid]}" for nid in ids)
    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)),
               TPUJOB_TRACE_DIR=trace_dir)

    def spawn_store(nid: str) -> "subprocess.Popen":
        peers = ",".join(
            f"{o}={direct[o] if o == nid else proxies[f'{nid}->{o}'].url}"
            for o in ids
        )
        return subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
             "--store", f"sqlite:{os.path.join(tmp, nid + '.db')}",
             "--listen", f"127.0.0.1:{ports[nid]}",
             "--log-capacity", "65536",
             "--replica-id", nid, "--peers", peers,
             "--advertise", advertise,
             "--peer-token-file", tok_path,
             # a 0.5s lease churns under load (proxied peer RPCs ride the
             # chaos seam): 2s rides out spikes; the ONE deliberate kill
             # still fails over in ~2 leases
             "--replica-lease-duration", "2.0",
             "--replica-retry-period", "0.2",
             "--replica-seed", str(seed)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, nid + ".log"), "w"),
        )

    def wait_leader(timeout: float = 20.0):
        # ONE probe implementation for smoke + bench (replica_wire owns
        # the status-probe protocol)
        return wait_for_wire_leader(direct, timeout)

    class StoreLeaderTarget:
        """kill = SIGKILL the current leader PROCESS (resolved at fire
        time via the status probe — the real deployed failure)."""

        def __init__(self):
            self.killed = None
            self.killed_at = None  # wall clock, for the trace bar

        def kill(self):
            lead = wait_leader(5.0)
            if lead is None:
                raise RuntimeError("no leader to kill")
            self.killed = lead
            self.killed_at = time.time()
            store_procs[lead].send_signal(_signal.SIGKILL)
            store_procs[lead].wait()

        def term(self):
            self.kill()

    store_procs = {}
    fleet_proc = operator_proc = None
    urls = list(direct.values())
    client = wclient = None
    stop_writer = threading.Event()
    acked = {}
    out: dict = {
        "metric": "controlplane_torture", "nodes": nodes, "jobs": jobs,
        "pods_per_job": pods, "seed": seed, "ok": False,
    }
    try:
        for nid in ids:
            store_procs[nid] = spawn_store(nid)
        first_leader = wait_leader()
        if first_leader is None:
            out["error"] = "no initial leader"
            return out
        client = HttpStoreClient(urls, timeout=60.0,
                                 conn_refused_retries=20,
                                 retry_base_delay=0.05)
        wclient = HttpStoreClient(urls, timeout=10.0,
                                  conn_refused_retries=20,
                                  retry_base_delay=0.05)
        # the REAL operator binary: controller + gang scheduler + node
        # monitor + informer, multi-endpoint store client
        operator_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.opshell",
             "--store", ",".join(urls), "--executor", "none",
             "--threadiness", str(threadiness),
             "--monitoring-port", str(mport),
             # hollow heartbeats every 5s; 30s grace rides out the
             # failover window without spurious NodeLost evictions
             "--node-grace", "30", "--event-ttl", "600"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, "operator.log"), "w"),
        )
        fleet_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.executor.hollow",
             "--store", ",".join(urls), "--nodes", str(nodes),
             "--chips", str(chips), "--run-s", str(run_s),
             "--heartbeat", "5", "--seed", str(seed)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, "fleet.log"), "w"),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if len(client.list("Node")) >= nodes:
                    break
            except Exception:
                pass
            time.sleep(0.5)

        def writer():
            """Marker writes: the no-acked-write-lost probe. Only
            DEFINITE acks join the must-survive set; indeterminate
            outcomes burn the name (the documented contract)."""
            i = 0
            while not stop_writer.is_set():
                try:
                    o = wclient.create(ConfigMap(metadata=_Meta(
                        name=f"m{i:05d}", namespace="torture")))
                    acked[o.metadata.name] = o.metadata.resource_version
                except Exception:
                    pass
                i += 1
                stop_writer.wait(0.05)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        # arm the chaos once traffic flows: partition the leader from
        # one follower, then SIGKILL the leader mid-partition
        other = next(o for o in ids if o != first_leader)
        script = ChaosScript.parse({
            "seed": seed,
            "actions": [
                {"at": 10.0, "fault": "partition", "a": first_leader,
                 "b": other, "duration": 6.0},
                {"at": 13.0, "fault": "kill", "target": "leader"},
            ],
        })
        target = StoreLeaderTarget()
        chaos = ChaosController(
            script, targets={"leader": target}, fabric=fabric,
        ).arm()

        t0 = time.perf_counter()
        submitted = 0
        done = 0
        deadline = time.time() + float(os.environ.get(
            "BENCH_CP_TORTURE_DEADLINE_S", max(600.0, jobs * 0.6)))
        while time.time() < deadline:
            try:
                done = sum(1 for j in client.list("TPUJob", "bench")
                           if cond.is_succeeded(j.status))
            except Exception:
                pass  # failover window: last count stands this tick
            while submitted < jobs and submitted - done < wave:
                try:
                    client.create(_make_job(submitted, pods, clean="All"))
                except AlreadyExists:
                    # an indeterminate create that actually COMMITTED
                    # (leader died between commit and response): the job
                    # exists — counting it submitted is the only exit, or
                    # this index re-rejects forever and the run wedges
                    pass
                except Exception:
                    break  # failover window: retry this index next tick
                submitted += 1
            if done >= jobs and chaos.done():
                break
            time.sleep(1.0)
        elapsed = time.perf_counter() - t0
        chaos.join(10.0)
        chaos_errors = [e for _, _, e in chaos.executed if e]
        stop_writer.set()
        # a writer blocked in a failover-window request can outlive a
        # short join; the verification below iterates `acked`, so wait
        # generously and then SNAPSHOT it (a late in-flight ack would
        # otherwise mutate the dict mid-iteration)
        wt.join(30.0)
        new_leader = wait_leader()
        out.update({
            "hollow_run_s": run_s,
            "jobs_succeeded": done,
            "elapsed_s": round(elapsed, 1),
            "jobs_per_s": round(done / max(1e-9, elapsed), 1),
            "leader_killed": target.killed,
            "new_leader": new_leader,
            "chaos_errors": chaos_errors,
            "acked_markers": len(acked),
        })

        # --- SLOs, read from the OPERATOR's real /metrics exposition ---
        expo = ""
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=10.0
            ) as r:
                expo = r.read().decode()
        except Exception as e:
            out["metrics_error"] = str(e)
        for q, tag in ((0.50, "p50"), (0.99, "p99")):
            for key, family in (
                ("reconcile", "tpu_operator_reconcile_latency_seconds"),
                ("bind", "tpu_operator_scheduler_bind_latency_seconds"),
                ("watch_lag", "tpu_operator_watch_delivery_lag_seconds"),
            ):
                try:
                    out[f"{key}_{tag}_ms"] = round(
                        exposition_quantile(expo, family, q) * 1e3, 2)
                except (KeyError, ValueError):
                    out[f"{key}_{tag}_ms"] = -1.0
        out["slo"] = {"reconcile_p99_ms": slo_reconcile,
                      "bind_p99_ms": slo_bind,
                      "watch_lag_p99_ms": slo_lag}
        slo_ok = (0 <= out["reconcile_p99_ms"] <= slo_reconcile
                  and 0 <= out["bind_p99_ms"] <= slo_bind
                  and 0 <= out["watch_lag_p99_ms"] <= slo_lag)
        out["slo_ok"] = bool(slo_ok)

        # --- the acked-write bar: every DEFINITE ack at its exact rv ---
        lost = []
        lead_client = HttpStoreClient(direct[new_leader], timeout=30.0) \
            if new_leader else None
        acked_snapshot = dict(acked)
        try:
            for name, rv in acked_snapshot.items():
                try:
                    got = lead_client.get("ConfigMap", "torture", name)
                    if got.metadata.resource_version != rv:
                        lost.append((name, rv,
                                     got.metadata.resource_version))
                except Exception as e:
                    lost.append((name, rv, f"missing: {e}"))
        finally:
            if lead_client is not None:
                lead_client.close()
        out["acked_lost"] = lost[:10]

        # --- the connected failover trace ------------------------------
        time.sleep(0.5)  # let the subprocess 0.2s flushers drain
        spans = trace.load_spans(trace_dir)
        elections = [s for s in spans
                     if s.get("name") == "replica.election"
                     and (s.get("attrs") or {}).get("won")]
        trace_ok, trace_why = False, ""
        if not elections:
            trace_why = "no winning election span"
        else:
            win = max(elections, key=lambda s: s.get("start") or 0)
            comps = trace.connected_components(spans, link_traces=True)
            comp = next(c for c in comps if win["span_id"] in c)
            in_comp = [s for s in spans if s["span_id"] in comp]
            names = {s["name"] for s in in_comp}
            kill_wall = target.killed_at or 0
            post_rec = [s for s in in_comp
                        if s["name"] == "controller.reconcile"
                        and (s.get("start") or 0) > kill_wall]
            if not win.get("parent_id"):
                trace_why = "election span unanchored"
            elif "replica.ship" not in names:
                trace_why = "no ship span connected"
            elif "store.request" not in names:
                trace_why = "no write span connected"
            elif not post_rec:
                trace_why = "no post-failover reconcile connected"
            else:
                trace_ok = True
        out["trace_connected"] = trace_ok
        if trace_why:
            out["trace_why"] = trace_why

        # --- ctl renders the incident rc=0 ------------------------------
        from mpi_operator_tpu.opshell import ctl

        import contextlib
        import io

        old_trace_dir = os.environ.get("TPUJOB_TRACE_DIR")
        os.environ["TPUJOB_TRACE_DIR"] = trace_dir
        try:
            # the render itself is operator-facing; the bench only needs
            # the rc — swallow the (large) timeline so the bench's stdout
            # stays one JSON line per mode
            with contextlib.redirect_stdout(io.StringIO()):
                rc = ctl.main(["--store", direct[new_leader or "n1"],
                               "trace", "--last-incident"])
        finally:
            if old_trace_dir is None:
                os.environ.pop("TPUJOB_TRACE_DIR", None)
            else:
                os.environ["TPUJOB_TRACE_DIR"] = old_trace_dir
        out["ctl_trace_rc"] = rc

        out["ok"] = bool(
            done >= jobs
            and not lost
            and not chaos_errors
            and target.killed is not None
            and new_leader is not None
            and new_leader != target.killed
            and len(acked) >= 20
            and slo_ok
            and trace_ok
            and rc == 0
        )
        return out
    finally:
        stop_writer.set()
        for c in (client, wclient):
            if c is not None:
                c.close()
        for proxy in proxies.values():
            proxy.stop()
        procs = [operator_proc, fleet_proc] + list(store_procs.values())
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        if os.environ.get("BENCH_CP_TORTURE_KEEP"):
            print(f"torture dir kept: {tmp}", file=sys.stderr)
        else:
            shutil.rmtree(tmp, ignore_errors=True)


def run_serve_mode() -> dict:
    """The serving workload class under traffic (BENCH_CP_MODES=serve,
    ISSUE 11): a hollow fleet hosts ONE autoscaled TPUServe sharing the
    cluster with a batch backlog, driven by a diurnal-plus-spike offered-
    load curve through the closed loop the autoscaler actually lives in
    (ServeLoadModel: more replicas → lower per-pod load → lower latency).

    Asserted (the slo block):
    - the autoscaler TRACKS the curve: peak ready replicas >= 4× the
      baseline, and the quiet tail scales to ZERO;
    - a mid-run rolling update completes with ZERO unready windows
      (ready gangs never dip below desired while rolling);
    - serve-readiness p99 (creation → every member ready, from the PR 9
      histogram) within BENCH_CP_SLO_SERVE_READY_P99_MS;
    - the batch backlog still FINISHES: serving scale-up preempts batch
      gangs (priority high > default), preempted jobs restart for free
      and reach Succeeded — the preempt+resume visible in `ctl trace`.
    """
    import io
    import contextlib
    import threading

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.api.client import TPUServeClient
    from mpi_operator_tpu.controller.autoscaler import (
        ANNOTATION_OFFERED_QPS,
        ServeAutoscaler,
    )
    from mpi_operator_tpu.controller.serve import (
        LABEL_SERVE_NAME,
        TPUServeController,
        group_replicas,
        replica_ready,
    )
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        ServeLoadModel,
    )
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.opshell import ctl, metrics

    nodes = int(os.environ.get("BENCH_CP_SERVE_NODES", "10"))
    batch_jobs = int(os.environ.get("BENCH_CP_SERVE_BATCH_JOBS", "24"))
    batch_pods = int(os.environ.get("BENCH_CP_SERVE_BATCH_PODS", "4"))
    batch_run_s = float(os.environ.get("BENCH_CP_SERVE_BATCH_RUN_S", "4.0"))
    spike_qps = float(os.environ.get("BENCH_CP_SERVE_SPIKE_QPS", "1200"))
    base_qps = float(os.environ.get("BENCH_CP_SERVE_BASE_QPS", "80"))
    # the cold-start bar from THE SLO config file (serve-ready entry;
    # env override preserved) — the same objective the runtime monitor
    # burn-rate-alerts on
    slo_ready_p99_ms = _slo_ms("serve-ready")

    tmp = tempfile.mkdtemp(prefix="bench-cp-serve-")
    trace_dir = os.path.join(tmp, "traces")
    trace.TRACER.configure("bench-serve", dir=trace_dir)
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0,
                         log_capacity=65536).start()
    client = HttpStoreClient(server.url, timeout=30.0,
                             watch_poll_timeout=2.0)
    fleet_client = HttpStoreClient(server.url, timeout=30.0,
                                   watch_poll_timeout=2.0)
    load = ServeLoadModel(capacity_qps=150.0, base_ms=20.0)
    timeline = HollowTimeline(
        pending_s=0.05, run_s=batch_run_s, seed=11,
        serve_warmup_s=0.4, serve_stats_interval_s=0.25, load=load,
    )
    snaps = {"ready": metrics.serve_ready_latency.snapshot()}
    preempted0 = metrics.gangs_preempted.get()
    cache = InformerCache(client).start()
    recorder = EventRecorder(client)
    controller = TPUJobController(
        client, recorder, ControllerOptions(threadiness=4), cache=cache)
    serve_controller = TPUServeController(client, recorder, cache=cache)
    scheduler = GangScheduler(client, recorder, cache=cache,
                              preemption_grace=0.5)
    autoscaler = ServeAutoscaler(client, recorder, cache=cache,
                                 interval=0.5)
    fleet = None
    serve_key = "bench/svc"
    samples = []          # (t, offered, desired, ready)
    rollout_dips = []
    try:
        if not cache.wait_for_sync(30.0):
            raise RuntimeError("informer cache never synced")
        fleet = HollowFleet(fleet_client, nodes, timeline=timeline,
                            capacity_chips=4,
                            heartbeat_interval=2.0).start()
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(cache.list("Node")) >= nodes:
                break
            time.sleep(0.1)
        controller.run()
        serve_controller.run()
        scheduler.start()
        autoscaler.start()

        sc = TPUServeClient(client, namespace="bench")
        sc.create({
            "kind": "TPUServe",
            "metadata": {"name": "svc", "namespace": "bench"},
            "spec": {
                "replicas": 1,
                "workers_per_replica": 1,
                "slice": {"accelerator": "cpu", "chips_per_host": 2},
                "autoscale": {
                    "min_replicas": 0, "max_replicas": 12,
                    "target_qps_per_replica": 100.0,
                    "scale_up_stabilization_s": 0.0,
                    "scale_down_stabilization_s": 3.0,
                    "scale_to_zero_after_s": 6.0,
                    "cold_start_grace_s": 2.0,
                },
            },
        })
        # the batch backlog, submitted up front: it must share the
        # cluster AND eventually finish despite the serving spike
        for i in range(batch_jobs):
            job = _make_job(i, batch_pods, clean="All")
            job.spec.slice.chips_per_host = 2
            job.spec.slots_per_worker = 2
            job.spec.worker.restart_policy = "OnFailure"
            client.create(job)

        def offered(qps: float) -> None:
            load.set_offered(serve_key, qps)
            client.patch("TPUServe", "bench", "svc", {"metadata": {
                "annotations": {ANNOTATION_OFFERED_QPS: str(qps)}}})

        def serve_counts():
            pods = [p for p in client.list(
                "Pod", "bench", selector={LABEL_SERVE_NAME: "svc"})
                if not p.is_finished()]
            ready = sum(1 for m in group_replicas(pods).values()
                        if replica_ready(m, 1))
            serve = client.get("TPUServe", "bench", "svc")
            return serve, ready

        def observe(tag: str, qps: float) -> int:
            serve, ready = serve_counts()
            samples.append({
                "t": round(time.time() - t0, 1), "phase": tag,
                "offered_qps": qps,
                "desired": serve.spec.replicas, "ready": ready,
            })
            return ready

        t0 = time.time()
        # --- phase 1: diurnal baseline ---
        offered(base_qps)
        while time.time() - t0 < 8.0:
            observe("baseline", base_qps)
            time.sleep(0.5)
        baseline_ready = max(1, observe("baseline", base_qps))
        # --- phase 2: the spike (serving must displace batch) ---
        offered(spike_qps)
        peak_ready = 0
        while time.time() - t0 < 30.0:
            peak_ready = max(peak_ready, observe("spike", spike_qps))
            time.sleep(0.5)
        # --- phase 3: settle to a mid plateau, then roll the template ---
        offered(300.0)
        plateau_deadline = time.time() + 20
        while time.time() < plateau_deadline:
            serve, ready = serve_counts()
            if serve.spec.replicas == 3 and ready == 3 \
                    and serve.status.updated_replicas == 3:
                break
            observe("settle", 300.0)
            time.sleep(0.5)
        rollout_desired = 3
        s2 = sc.get("svc")
        s2.spec.template.container.env = {"MODEL": "v2"}
        sc.update(s2)
        rollout_deadline = time.time() + 30
        rollout_converged = False
        while time.time() < rollout_deadline:
            serve, ready = serve_counts()
            observe("rollout", 300.0)
            if ready < min(rollout_desired, serve.spec.replicas or 0):
                rollout_dips.append({"t": round(time.time() - t0, 1),
                                     "ready": ready,
                                     "desired": serve.spec.replicas})
            st = serve.status
            if (st.serve_generation == 1
                    and st.updated_replicas == (serve.spec.replicas or 0)
                    and st.replicas == (serve.spec.replicas or 0)
                    and ready == (serve.spec.replicas or 0)):
                rollout_converged = True
                break
            time.sleep(0.25)
        # --- phase 4: traffic dies; scale-to-zero ---
        offered(0.0)
        zero_deadline = time.time() + 30
        scaled_to_zero = False
        while time.time() < zero_deadline:
            serve, ready = serve_counts()
            observe("quiet", 0.0)
            if (serve.spec.replicas or 0) == 0 and serve.status.replicas == 0:
                scaled_to_zero = True
                break
            time.sleep(0.5)
        # --- batch must still finish (preempted gangs resumed) ---
        batch_deadline = time.time() + float(os.environ.get(
            "BENCH_CP_SERVE_BATCH_DEADLINE_S", "120"))
        done = 0
        while time.time() < batch_deadline:
            done = sum(
                1 for j in client.list("TPUJob", "bench")
                if cond.is_succeeded(j.status)
            )
            if done >= batch_jobs:
                break
            time.sleep(1.0)
        elapsed = time.time() - t0

        preempted = metrics.gangs_preempted.get() - preempted0
        # the preempt→restart causality, straight from the span trail: a
        # FREE gang restart is the resume half of a preemption
        trace.TRACER.flush()
        spans = trace.load_spans(trace_dir)
        free_restarts = [
            s for s in spans if s.get("name") == "controller.gang_restart"
            and (s.get("attrs") or {}).get("free")
        ]
        ctl_trace_rc = None
        ctl_trace_has_restart = False
        if free_restarts:
            job_key = free_restarts[0]["attrs"]["job"]
            job_name = job_key.split("/", 1)[1]
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                ctl_trace_rc = ctl.main([
                    "--store", server.url, "-n", "bench",
                    "trace", job_name, "--trace-dir", trace_dir,
                ])
            ctl_trace_has_restart = "gang_restart" in buf.getvalue()

        ready_p99_ms = round(_hist_quantile_delta(
            metrics.serve_ready_latency, 0.99, snaps["ready"],
            metrics.serve_ready_latency.snapshot()) * 1e3, 1)
        ready_latencies = sorted(
            round(float((s.get("attrs") or {}).get("ready_latency_s", 0)), 2)
            for s in spans if s.get("name") == "serve.replica_ready"
        )
        out = {
            "metric": "controlplane_serve",
            "nodes": nodes,
            "chips": nodes * 4,
            "batch_jobs": batch_jobs,
            "batch_pods_per_job": batch_pods,
            "baseline_ready": baseline_ready,
            "peak_ready": peak_ready,
            "spike_factor": round(peak_ready / max(1, baseline_ready), 1),
            "rollout_converged": rollout_converged,
            "rollout_unready_windows": len(rollout_dips),
            "scaled_to_zero": scaled_to_zero,
            "batch_succeeded": done,
            "gangs_preempted": int(preempted),
            "free_gang_restarts": len(free_restarts),
            "ctl_trace_rc": ctl_trace_rc,
            "ctl_trace_shows_restart": ctl_trace_has_restart,
            "serve_ready_p99_ms": ready_p99_ms,
            "ready_latencies_s": ready_latencies,
            "elapsed_s": round(elapsed, 1),
            "timeline": samples[-60:],
        }
        out["slo"] = {
            "spike_factor_min": 4.0,
            "serve_ready_p99_ms": slo_ready_p99_ms,
            "rollout_unready_windows": 0,
        }
        out["slo_ok"] = bool(
            out["spike_factor"] >= 4.0
            and rollout_converged
            and not rollout_dips
            and scaled_to_zero
            and done >= batch_jobs
            and preempted > 0
            and ready_p99_ms <= slo_ready_p99_ms
            and ctl_trace_rc == 0
            and ctl_trace_has_restart
        )
        return out
    finally:
        for comp in (autoscaler, serve_controller, controller):
            try:
                comp.stop()
            except Exception:
                pass
        scheduler.stop()
        if fleet is not None:
            fleet.stop()
        cache.stop()
        client.close()
        fleet_client.close()
        server.stop()
        backing.close()
        trace.TRACER.disable()


def run_drain_mode(seed: int) -> dict:
    """The disruption plane under rolling maintenance (BENCH_CP_MODES=
    drain, ISSUE 14): a hollow fleet hosts a DisruptionBudget-protected
    TPUServe plus a live batch backlog while a seeded maintenance wave
    rolls over 20% of the nodes (notice → cordon → checkpoint-then-migrate
    → deadline), with ONE extra notice deliberately too short to drain in
    time (the escalation bar).

    Asserted (the slo block):
    - every batch job reaches Succeeded DESPITE the wave, with
      restart_count UNCHANGED (0) — planned moves never burn the
      backoffLimit budget — while >=1 gang shows restart_generation > 0
      (the migrations actually happened);
    - ZERO windows with serve ready below the DisruptionBudget;
    - every noticed node drains EMPTY and the deliberate overrun is
      hard-evicted (drains_total{outcome=escalated} >= 1);
    - SLOs green: reconcile/bind p99 within the slo_defaults.json bars,
      drain-migration p99 within its objective threshold;
    - the trace renders the story: ONE connected component holds the
      notice (drain.node) → migration (drain.migrate_gang) → restart
      (controller.gang_restart) chain, the escalated node's component
      holds drain.escalate → drain.hard_evict → restart (the
      maintenance-fire chain), and `ctl trace <job>` exits 0.
    """
    import io
    import contextlib
    import threading

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.api.client import TPUServeClient
    from mpi_operator_tpu.controller.disruption import DrainController
    from mpi_operator_tpu.controller.node_monitor import NodeMonitor
    from mpi_operator_tpu.controller.serve import TPUServeController
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        MaintenanceSchedule,
    )
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        NODE_NAMESPACE,
    )
    from mpi_operator_tpu.opshell import ctl, metrics

    nodes = int(os.environ.get("BENCH_CP_DRAIN_NODES", "100"))
    fraction = float(os.environ.get("BENCH_CP_DRAIN_FRACTION", "0.2"))
    batch_jobs = int(os.environ.get("BENCH_CP_DRAIN_BATCH_JOBS", "40"))
    batch_pods = int(os.environ.get("BENCH_CP_DRAIN_BATCH_PODS", "2"))
    batch_run_s = float(os.environ.get("BENCH_CP_DRAIN_BATCH_RUN_S", "6.0"))
    notice_s = float(os.environ.get("BENCH_CP_DRAIN_NOTICE_S", "10.0"))
    serve_replicas = 6
    budget = 5
    slo_reconcile = _slo_ms("reconcile-latency")
    slo_bind = _slo_ms("scheduler-bind")
    slo_drain = _slo_ms("drain-migration")

    tmp = tempfile.mkdtemp(prefix="bench-cp-drain-")
    trace_dir = os.path.join(tmp, "traces")
    trace.TRACER.configure("bench-drain", dir=trace_dir)
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0,
                         log_capacity=65536).start()
    client = HttpStoreClient(server.url, timeout=30.0,
                             watch_poll_timeout=2.0)
    fleet_client = HttpStoreClient(server.url, timeout=30.0,
                                   watch_poll_timeout=2.0)
    timeline = HollowTimeline(pending_s=0.05, run_s=batch_run_s,
                              run_jitter_s=2.0, seed=seed,
                              serve_warmup_s=0.3)
    snaps = {
        "reconcile": metrics.reconcile_latency.snapshot(),
        "bind": metrics.scheduler_bind_latency.snapshot(),
        "drain": metrics.drain_migration_latency.snapshot(),
    }
    escalated0 = metrics.drains_total.get(outcome="escalated")
    cache = InformerCache(client).start()
    recorder = EventRecorder(client)
    controller = TPUJobController(
        client, recorder, ControllerOptions(threadiness=4), cache=cache)
    serve_controller = TPUServeController(client, recorder, cache=cache)
    scheduler = GangScheduler(client, recorder, cache=cache)
    monitor = NodeMonitor(client, recorder, cache=cache)
    drain = DrainController(client, recorder, interval=0.2, cache=cache)
    fleet = None
    samples = []
    min_ready = [serve_replicas]
    try:
        if not cache.wait_for_sync(30.0):
            raise RuntimeError("informer cache never synced")
        fleet = HollowFleet(fleet_client, nodes, timeline=timeline,
                            capacity_chips=4,
                            heartbeat_interval=2.0).start()
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(cache.list("Node")) >= nodes:
                break
            time.sleep(0.1)
        controller.run()
        serve_controller.run()
        scheduler.start()
        monitor.start()
        drain.start()

        TPUServeClient(client, namespace="bench").create({
            "kind": "TPUServe",
            "metadata": {"name": "svc", "namespace": "bench"},
            "spec": {
                "replicas": serve_replicas, "workers_per_replica": 1,
                "slice": {"accelerator": "cpu", "chips_per_host": 2},
                "disruption_budget": budget, "max_surge": 2,
                "max_unavailable": 1,
            },
        })
        for i in range(batch_jobs):
            job = _make_job(i, batch_pods, clean="None")
            job.spec.worker.restart_policy = "OnFailure"
            client.create(job)

        def serve_ready() -> int:
            s = client.try_get("TPUServe", "bench", "svc")
            return s.status.ready_replicas if s else 0

        def succeeded() -> int:
            return sum(1 for j in client.list("TPUJob", "bench")
                       if cond.is_succeeded(j.status))

        t0 = time.time()
        deadline = time.time() + 60
        while time.time() < deadline and serve_ready() < serve_replicas:
            time.sleep(0.2)
        if serve_ready() < serve_replicas:
            raise RuntimeError("serve never reached full readiness")
        deadline = time.time() + 30
        while time.time() < deadline and not any(
            p.status.phase == "Running"
            for p in cache.list("Pod", "bench")
        ):
            time.sleep(0.2)

        # --- the rolling wave: 20% of the fleet, seeded, staggered -----
        sched_m = MaintenanceSchedule(fraction=fraction, notice_s=notice_s,
                                      start_s=0.5, stagger_s=0.4,
                                      seed=seed)
        victims = sched_m.victims(fleet.node_names)
        fleet.arm_maintenance(sched_m)
        # ... plus ONE deliberate overrun: a node with live pods and a
        # notice far too short to drain gracefully → must hard-evict
        overrun = None
        deadline = time.time() + 30
        while overrun is None and time.time() < deadline:
            for p in cache.list("Pod", "bench"):
                n = p.spec.node_name
                if (n and n not in victims and not p.is_finished()
                        and p.status.phase == "Running"):
                    overrun = n
                    break
            time.sleep(0.1)
        if overrun is None:
            raise RuntimeError("no node eligible for the overrun probe")
        # zero-warning reclaim: the deadline is already PAST when the
        # notice lands, so the first drain tick must hard-evict (a
        # graceful migration is store-instant and would beat any
        # realistically short window)
        fleet.announce_maintenance(overrun, time.time() - 0.1)

        # --- drive to completion, sampling the budget every 100ms ------
        sample_stop = threading.Event()

        def sampler():
            while not sample_stop.is_set():
                r = serve_ready()
                min_ready[0] = min(min_ready[0], r)
                samples.append({"t": round(time.time() - t0, 1),
                                "ready": r})
                sample_stop.wait(0.1)

        st = threading.Thread(target=sampler, daemon=True)
        st.start()
        run_deadline = time.time() + float(os.environ.get(
            "BENCH_CP_DRAIN_DEADLINE_S", "180"))
        done = 0
        while time.time() < run_deadline:
            done = succeeded()
            if done >= batch_jobs:
                break
            time.sleep(0.5)
        # every noticed node must drain EMPTY (cordoned, nothing live)
        all_noticed = victims + [overrun]
        drained_deadline = time.time() + 60
        remaining = all_noticed
        while time.time() < drained_deadline:
            live = {p.spec.node_name for p in cache.list("Pod")
                    if p.spec.node_name and not p.is_finished()}
            remaining = [n for n in all_noticed if n in live]
            if not remaining:
                break
            time.sleep(0.5)
        # serve settles back to full strength off the drained nodes
        settle_deadline = time.time() + 60
        while time.time() < settle_deadline \
                and serve_ready() < serve_replicas:
            time.sleep(0.2)
        sample_stop.set()
        st.join(timeout=2)
        elapsed = time.time() - t0

        jobs_all = client.list("TPUJob", "bench")
        migrated = [j for j in jobs_all
                    if j.status.restart_generation > 0]
        burned = [j.metadata.name for j in jobs_all
                  if j.status.restart_count > 0]
        escalated = metrics.drains_total.get(
            outcome="escalated") - escalated0

        # --- the trace story -------------------------------------------
        trace.TRACER.flush()
        spans = trace.load_spans(trace_dir)
        comps = trace.connected_components(spans, link_traces=True)
        by_id = {s["span_id"]: s for s in spans if "span_id" in s}

        def component_names(comp):
            return {by_id[sid]["name"] for sid in comp if sid in by_id}

        migrate_chain = any(
            {"drain.node", "drain.migrate_gang",
             "controller.gang_restart"} <= component_names(c)
            for c in comps
        )
        fire_chain = any(
            {"drain.node", "drain.escalate", "drain.hard_evict",
             "controller.gang_restart"} <= component_names(c)
            for c in comps
        )
        ctl_trace_rc = None
        if migrated:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                ctl_trace_rc = ctl.main([
                    "--store", server.url, "-n", "bench",
                    "trace", migrated[0].metadata.name,
                    "--trace-dir", trace_dir,
                ])

        out = {
            "metric": "controlplane_drain",
            "seed": seed,
            "nodes": nodes,
            "noticed_nodes": len(all_noticed),
            "batch_jobs": batch_jobs,
            "batch_succeeded": done,
            "gangs_migrated": len(migrated),
            "jobs_with_burned_backoff": burned,
            "serve_replicas": serve_replicas,
            "disruption_budget": budget,
            "min_ready_during_wave": min_ready[0],
            "budget_violation_windows": sum(
                1 for s in samples if s["ready"] < budget),
            "drains_escalated": int(escalated),
            "nodes_never_drained": remaining,
            "trace_migrate_chain_connected": migrate_chain,
            "trace_fire_chain_connected": fire_chain,
            "ctl_trace_rc": ctl_trace_rc,
            "elapsed_s": round(elapsed, 1),
            "timeline_tail": samples[-40:],
        }
        for q, tag in ((0.50, "p50"), (0.99, "p99")):
            out[f"reconcile_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.reconcile_latency, q, snaps["reconcile"],
                metrics.reconcile_latency.snapshot()) * 1e3, 2)
            out[f"bind_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.scheduler_bind_latency, q, snaps["bind"],
                metrics.scheduler_bind_latency.snapshot()) * 1e3, 2)
            out[f"drain_migration_{tag}_ms"] = round(_hist_quantile_delta(
                metrics.drain_migration_latency, q, snaps["drain"],
                metrics.drain_migration_latency.snapshot()) * 1e3, 2)
        out["slo"] = {
            "reconcile_p99_ms": slo_reconcile,
            "bind_p99_ms": slo_bind,
            "drain_migration_p99_ms": slo_drain,
            "budget_violation_windows": 0,
        }
        out["ok"] = bool(
            done >= batch_jobs
            and not burned
            and migrated
            and min_ready[0] >= budget
            and out["budget_violation_windows"] == 0
            and escalated >= 1
            and not remaining
            and migrate_chain
            and fire_chain
            and ctl_trace_rc == 0
            and out["reconcile_p99_ms"] <= slo_reconcile
            and out["bind_p99_ms"] <= slo_bind
            and out["drain_migration_p99_ms"] <= slo_drain
        )
        return out
    finally:
        try:
            sample_stop.set()
        except NameError:
            pass
        drain.stop()
        monitor.stop()
        for comp in (serve_controller, controller):
            try:
                comp.stop()
            except Exception:
                pass
        scheduler.stop()
        if fleet is not None:
            fleet.stop()
        cache.stop()
        client.close()
        fleet_client.close()
        server.stop()
        backing.close()
        trace.TRACER.disable()


def _soak_scenario(seed: int, day: float, scale: float,
                   reclaim_target: str) -> dict:
    """One compressed fleet-day (ISSUE 18): a diurnal serve curve, two
    seeded batch tenants (2-chip fragmenters + 4-chip whole-node gangs),
    one rolling maintenance wave, one zero-warning reclaim."""
    return {
        "seed": seed, "scale": scale, "duration": day,
        "serves": [{"serve": "soak/web", "curve": "diurnal",
                    "peak_qps": 80.0, "trough_qps": 10.0,
                    "period": day, "interval": day / 24.0}],
        "arrivals": [
            # the fragmenters: LONG-lived 2-pod × 2-chip gangs the
            # least-loaded scheduler scatters across half-full nodes —
            # without the rescheduler the scatter persists for hours of
            # scenario time (fast-churning jobs would defragment the
            # baseline by natural attrition and hide the effect)
            {"tenant": "etl", "rate_per_hour": 5.0, "pods": 2,
             "chips": 2, "end": day * 0.8},
            # the whole-node gangs the fragmentation blocks — frequent
            # enough that the sampler catches them queued (the A/B gate
            # is starved-while-queued windows, which needs demand), but
            # below saturation: starvation must come from SCATTER, not
            # from a fleet with genuinely zero free chips (the
            # rescheduler cannot conjure capacity, only compact it)
            {"tenant": "train", "rate_per_hour": 3.0, "pods": 1,
             "chips": 4, "end": day * 0.8},
        ],
        "maintenance": [{"at": day * 0.35, "fraction": 0.2,
                         "notice": 600.0, "stagger": 120.0}],
        "chaos": [{"at": day * 0.7, "fault": "reclaim",
                   "target": reclaim_target}],
    }


def _soak_arm(seed: int, *, rescheduler: bool, judge: bool) -> dict:
    """One arm of the soak A/B (BENCH_CP_MODES=soak, ISSUE 18): the
    deployed multi-process shape — three wire-replicated `tpu-store`
    processes and a real `tpu-operator` process (with or without
    `--no-rescheduler`) — hosting a scenario-driven hollow fleet (the
    fleet rides the bench process over its own wire client so the
    scenario engine can set serve load, arm waves and fire the reclaim).
    When ``judge`` is set, an SLOMonitor with compressed burn windows
    scrapes the operator's real /metrics and its Alert objects are the
    acceptance bar: every page must be explained by a scripted
    disruption and carry a flight-recorder bundle that renders rc=0."""
    import shutil
    import subprocess
    import threading
    import urllib.request

    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.api.client import TPUServeClient
    from mpi_operator_tpu.api.types import ALERT_NAMESPACE
    from mpi_operator_tpu.controller.slo_monitor import (
        SLOMonitor,
        load_slo_config,
    )
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        ServeLoadModel,
    )
    from mpi_operator_tpu.machinery.objects import (
        ANNOTATION_MAINTENANCE_AT,
        NODE_NAMESPACE,
    )
    from mpi_operator_tpu.machinery.replica_wire import (
        free_ports,
        wait_for_wire_leader,
    )
    from mpi_operator_tpu.machinery.scenario import (
        Scenario,
        ScenarioEngine,
        VirtualClock,
    )
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.machinery.telemetry import ScrapeTarget
    from mpi_operator_tpu.opshell import ctl

    day = float(os.environ.get("BENCH_CP_SOAK_DAY_S", "21600"))
    scale = float(os.environ.get("BENCH_CP_SOAK_SCALE", "360"))
    nodes = int(os.environ.get("BENCH_CP_SOAK_NODES", "14"))
    serve_replicas = 4
    budget = 3
    reclaim_target = "hollow-0005"
    scenario = Scenario.parse(
        _soak_scenario(seed, day, scale, reclaim_target))
    clock = VirtualClock(scale)

    tmp = tempfile.mkdtemp(prefix="bench-cp-soak-")
    trace_dir = os.path.join(tmp, "traces")
    os.makedirs(trace_dir, exist_ok=True)
    # the judge's slo.alert spans are what `ctl trace --last-incident`
    # renders — they must land in the same dir the subprocesses write to
    trace.TRACER.configure("bench-soak", dir=trace_dir)
    ids = ["n0", "n1", "n2"]
    allocated = free_ports(4)
    ports = dict(zip(ids, allocated))
    mport = allocated[3]
    direct = {nid: f"http://127.0.0.1:{ports[nid]}" for nid in ids}
    urls = list(direct.values())
    tok_path = os.path.join(tmp, "peer.token")
    with open(tok_path, "w") as f:
        f.write("soak-peer-secret\n")
    advertise = ",".join(f"{nid}={direct[nid]}" for nid in ids)
    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)),
               TPUJOB_TRACE_DIR=trace_dir)

    def spawn_store(nid: str) -> "subprocess.Popen":
        peers = ",".join(f"{o}={direct[o]}" for o in ids)
        return subprocess.Popen(
            [sys.executable, "-m",
             "mpi_operator_tpu.machinery.http_store",
             "--store", f"sqlite:{os.path.join(tmp, nid + '.db')}",
             "--listen", f"127.0.0.1:{ports[nid]}",
             "--log-capacity", "65536",
             "--replica-id", nid, "--peers", peers,
             "--advertise", advertise,
             "--peer-token-file", tok_path,
             "--replica-lease-duration", "2.0",
             "--replica-retry-period", "0.2",
             "--replica-seed", str(seed)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, nid + ".log"), "w"),
        )

    store_procs: dict = {}
    operator_proc = None
    fleet = engine = monitor = None
    client = fleet_client = None
    sample_stop = threading.Event()
    out: dict = {"arm": "rescheduler" if rescheduler else "baseline",
                 "ok": False}
    # budget/fragmentation samples + page sightings, appended by the
    # sampler thread, read after join
    samples: list = []
    pages: dict = {}
    first_pending: dict = {}
    bound_at: dict = {}
    chips_by_job: dict = {}
    t0 = time.time()
    try:
        for nid in ids:
            store_procs[nid] = spawn_store(nid)
        if wait_for_wire_leader(direct, 20.0) is None:
            out["error"] = "no wire leader"
            return out
        client = HttpStoreClient(urls, timeout=30.0,
                                 conn_refused_retries=20,
                                 retry_base_delay=0.05)
        fleet_client = HttpStoreClient(urls, timeout=30.0,
                                       conn_refused_retries=20,
                                       retry_base_delay=0.05)
        operator_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.opshell",
             "--store", ",".join(urls), "--executor", "none",
             "--threadiness", "4",
             "--monitoring-port", str(mport),
             # the reclaim's free eviction must come from the drain
             # plane's escalation, not a NodeLost sweep: keep the grace
             # far beyond the drain interval
             "--node-grace", "30", "--event-ttl", "600",
             # the rescheduler's governance defaults assume a real day;
             # the compressed one needs the budget window compressed the
             # same way (2 moves/60s would be 2 moves per WHOLE day)
             "--reschedule-interval", "0.5",
             "--reschedule-max-moves", "4",
             "--reschedule-window", "15",
             # the judge runs in THIS process with compressed windows;
             # two monitors would flap each other's uid-pinned alerts
             "--no-slo-monitor"]
            + ([] if rescheduler else ["--no-rescheduler"]),
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, "operator.log"), "w"),
        )
        fleet = HollowFleet(
            fleet_client, nodes,
            timeline=HollowTimeline(
                pending_s=0.05, run_s=8.0, run_jitter_s=4.0, seed=seed,
                serve_warmup_s=0.3,
                load=ServeLoadModel(capacity_qps=200.0),
                # migrations resume from checkpoint (the operator's
                # contract) — without this every defrag move re-runs the
                # victim's whole clock and the A/B punishes the mover
                checkpoint_resume=True,
            ),
            capacity_chips=4, heartbeat_interval=2.0, clock=clock,
        ).start()
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if len(client.list("Node", NODE_NAMESPACE)) >= nodes:
                    break
            except Exception:
                pass
            time.sleep(0.2)

        TPUServeClient(client, namespace="soak").create({
            "kind": "TPUServe",
            "metadata": {"name": "web", "namespace": "soak"},
            "spec": {
                # whole-node replicas: a single node loss (the reclaim)
                # can cost at most ONE replica, which the budget absorbs
                "replicas": serve_replicas, "workers_per_replica": 1,
                "slice": {"accelerator": "cpu", "chips_per_host": 4},
                "disruption_budget": budget, "max_surge": 1,
                "max_unavailable": 1,
            },
        })

        def serve_ready() -> int:
            s = client.try_get("TPUServe", "soak", "web")
            return s.status.ready_replicas if s else 0

        deadline = time.time() + 60
        while time.time() < deadline and serve_ready() < serve_replicas:
            time.sleep(0.2)
        if serve_ready() < serve_replicas:
            raise RuntimeError("serve never reached full readiness")

        if judge:
            monitor = SLOMonitor(
                client,
                [ScrapeTarget("operator",
                              f"http://127.0.0.1:{mport}/metrics")],
                load_slo_config().scaled(1.0 / 600.0), interval=0.25,
                incident_dir=os.path.join(trace_dir, "incidents"),
            ).start()

        def observe():
            ns_ = client.list("Node", NODE_NAMESPACE)
            ps = [p for p in client.list("Pod") if not p.is_finished()]
            used = GangScheduler._node_used(ps)
            free = [
                max(0, (n.status.capacity_chips or 0)
                    - used.get(n.metadata.name, 0))
                for n in ns_
                if n.status.ready and not n.status.unschedulable
                and ANNOTATION_MAINTENANCE_AT not in n.metadata.annotations
            ]
            return sum(free), max(free or [0]), ps

        min_ready = [serve_replicas]

        def sampler():
            while not sample_stop.is_set():
                t = time.time() - engine_t0[0]
                try:
                    total, contig, ps = observe()
                    r = serve_ready()
                    min_ready[0] = min(min_ready[0], r)
                    pend = set()
                    for p in ps:
                        jn = p.metadata.labels.get("tpujob.dev/job-name")
                        if not jn or p.metadata.namespace != "soak":
                            continue
                        if not p.spec.node_name:
                            pend.add(jn)
                            first_pending.setdefault(jn, t)
                    for jn in list(first_pending):
                        if jn not in pend and jn not in bound_at:
                            bound_at[jn] = t
                    for j in client.list("TPUJob", "soak"):
                        chips_by_job[j.metadata.name] = \
                            j.spec.slice.chips_per_host
                    samples.append({
                        "t": round(t, 1), "free": total,
                        "contig": contig, "ready": r,
                        # a whole-node gang is QUEUED right now: the
                        # window where contiguous capacity is the number
                        # that matters (the A/B gate below)
                        "demand4": any(chips_by_job.get(jn) == 4
                                       for jn in pend),
                    })
                    if judge:
                        for a in client.list("Alert", ALERT_NAMESPACE):
                            if a.is_firing():
                                w = pages.setdefault(
                                    a.metadata.name, [t, t])
                                w[1] = t
                except Exception:
                    pass  # one missed sample must not end the day
                sample_stop.wait(0.2)

        engine_t0 = [time.time()]
        engine = ScenarioEngine(scenario, client, fleet=fleet,
                                clock=clock)
        st = threading.Thread(target=sampler, daemon=True)
        engine.start()
        engine_t0[0] = time.time()
        st.start()
        run_deadline = time.time() + day / scale + 60
        while time.time() < run_deadline and not engine.done():
            time.sleep(0.25)
        out["engine_done"] = engine.done()
        out["engine_errors"] = engine.errors()[:5]

        # drain out: every arrival gang must still finish
        def succeeded() -> int:
            n = 0
            for key in engine.submitted:
                ns_, name = key.split("/", 1)
                j = client.try_get("TPUJob", ns_, name)
                if j is not None and cond.is_succeeded(j.status):
                    n += 1
            return n
        deadline = time.time() + 60
        done = 0
        while time.time() < deadline:
            done = succeeded()
            if done >= len(engine.submitted):
                break
            time.sleep(0.5)
        sample_stop.set()
        st.join(timeout=3)

        jobs_all = client.list("TPUJob", "soak")
        burned = [j.metadata.name for j in jobs_all
                  if (j.status.restart_count or 0) > 0]
        waits = sorted(bound_at[j] - first_pending[j]
                       for j in bound_at if j in first_pending)
        out.update({
            "submitted": len(engine.submitted),
            "succeeded": done,
            "jobs_with_burned_backoff": burned,
            "min_ready_during_day": min_ready[0],
            "budget_violation_windows": sum(
                1 for s in samples if s["ready"] < budget),
            "contig_mean": round(statistics.fmean(
                s["contig"] for s in samples), 2) if samples else 0.0,
            "free_mean": round(statistics.fmean(
                s["free"] for s in samples), 2) if samples else 0.0,
            "queue_wait_p50_s": round(_percentile(waits, 0.5), 2)
            if waits else 0.0,
            "queue_wait_max_s": round(waits[-1], 2) if waits else 0.0,
        })
        # demand-conditioned fragmentation: raw contig means are polluted
        # by occupancy differences between the arms (the rescheduler's
        # own cordons + the unblocked gangs it lets run), so the gate is
        # "while a whole-node gang was queued, how often was the fleet
        # fragmented below it" — the exact window the gauge exists for
        demand = [s for s in samples if s.get("demand4")]
        starved = [s for s in demand if s["contig"] < 4]
        out.update({
            "demand_windows": len(demand),
            "starved_windows": len(starved),
            "starved_fraction": round(len(starved) / len(demand), 3)
            if demand else 0.0,
            "contig_under_demand": round(statistics.fmean(
                s["contig"] for s in demand), 2) if demand else 0.0,
        })

        # --- the pages: each one explained + bundled, or the day fails -
        if judge:
            wave_t = day * 0.35 / scale
            wave_end = wave_t + 600.0 / scale + 30.0
            reclaim_t = day * 0.7 / scale
            explained_windows = [(wave_t - 2.0, wave_end),
                                 (reclaim_t - 2.0, reclaim_t + 30.0)]
            # the scripted fragmentation is itself an explanation for
            # bind-latency pages: a gang the scenario starved binds
            # LATE, and that bind's latency burns the scheduler-bind
            # objective — the page is the antagonist doing its job, not
            # a mystery. Explained iff the sampler actually RECORDED a
            # starved-demand window within the burn horizon before the
            # firing (measured evidence, not a blanket waiver).
            starved_ts = [s["t"] for s in samples
                          if s.get("demand4") and s["contig"] < 4]

            def explained(name: str, first: float) -> bool:
                if any(lo <= first <= hi for lo, hi in explained_windows):
                    return True
                if name == "scheduler-bind":
                    return any(first - 60.0 <= st_ <= first
                               for st_ in starved_ts)
                return False

            unexplained = [
                name for name, (first, _last) in sorted(pages.items())
                if not explained(name, first)
            ]
            bundle_rcs = []
            for name in sorted(pages):
                a = client.try_get("Alert", ALERT_NAMESPACE, name)
                has_bundle = bool(
                    a is not None and a.status.incident
                    and os.path.exists(a.status.incident))
                rc = None
                if has_bundle:
                    import io
                    import contextlib
                    trace.TRACER.flush()
                    with contextlib.redirect_stdout(io.StringIO()):
                        rc = ctl.main(["--store", urls[0], "trace",
                                       "--last-incident",
                                       "--trace-dir", trace_dir])
                bundle_rcs.append({"page": name, "bundle": has_bundle,
                                   "ctl_trace_rc": rc})
            out["pages"] = {n: [round(a, 1), round(b, 1)]
                            for n, (a, b) in sorted(pages.items())}
            out["unexplained_pages"] = unexplained
            out["bundles"] = bundle_rcs
            out["pages_ok"] = bool(
                not unexplained
                and all(b["bundle"] and b["ctl_trace_rc"] == 0
                        for b in bundle_rcs))
        # --- the rescheduler's own numbers, from the REAL /metrics ----
        if rescheduler:
            expo = ""
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/metrics", timeout=10.0
                ) as r:
                    expo = r.read().decode()
            except Exception as e:
                out["metrics_error"] = str(e)
            out["contig_gauge_exported"] = (
                "tpu_operator_schedulable_contiguous_chips" in expo)
            resched_n = 0
            for line in expo.splitlines():
                if line.startswith("tpu_operator_reschedules_total{"):
                    try:
                        resched_n += int(float(line.rsplit(" ", 1)[1]))
                    except ValueError:
                        pass
            out["reschedules_total"] = resched_n

        out["elapsed_s"] = round(time.time() - t0, 1)
        out["ok"] = bool(
            out["engine_done"]
            and not out["engine_errors"]
            and out["submitted"] > 0
            and done >= len(engine.submitted)
            and not burned
            and out["budget_violation_windows"] == 0
            and (not judge or out["pages_ok"])
            and (not rescheduler
                 or (out["contig_gauge_exported"]
                     and out["reschedules_total"] >= 1))
        )
        return out
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out
    finally:
        sample_stop.set()
        if monitor is not None:
            monitor.stop()
        if engine is not None:
            engine.stop()
        if fleet is not None:
            fleet.stop()
        for c in (client, fleet_client):
            if c is not None:
                c.close()
        procs = [operator_proc] + list(store_procs.values())
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        trace.TRACER.disable()
        if os.environ.get("BENCH_CP_SOAK_KEEP"):
            print(f"soak dir kept: {tmp}", file=sys.stderr)
        else:
            shutil.rmtree(tmp, ignore_errors=True)


def run_soak_mode(seed: int) -> dict:
    """A day in the life of the fleet (BENCH_CP_MODES=soak, ISSUE 18):
    ONE seeded compressed fleet-day — diurnal serve traffic, two batch
    tenants, a rolling maintenance wave, a zero-warning reclaim — run as
    an A/B against the deployed multi-process shape: once with the
    rescheduler (the SLO plane judging: zero unexplained pages, every
    bundle rendering rc=0, zero burned backoffs, zero budget-violation
    windows) and once with `--no-rescheduler` as the fragmentation
    baseline. The bar the rescheduler must clear, in the same JSON:
    fewer starved windows — samples where a whole-node gang sat queued
    while `schedulable_contiguous_chips` was below its ask — than the
    baseline arm (raw contig means are reported but not gated on: the
    arms run different occupancy, so an unconditioned mean punishes the
    rescheduler for the very gangs it unblocked). The caller runs the
    whole A/B TWICE on one seed (scenario determinism)."""
    with_arm = _soak_arm(seed, rescheduler=True, judge=True)
    base_arm = _soak_arm(seed, rescheduler=False, judge=False)
    delta = round(
        with_arm.get("contig_mean", 0.0) - base_arm.get("contig_mean",
                                                        0.0), 2)
    return {
        "metric": "controlplane_soak",
        "seed": seed,
        "rescheduler": with_arm,
        "baseline": base_arm,
        "contig_mean_delta_chips": delta,
        "ok": bool(with_arm.get("ok") and base_arm.get("ok")
                   and base_arm.get("demand_windows", 0) > 0
                   and with_arm.get("starved_fraction", 1.0)
                   < base_arm.get("starved_fraction", 0.0)),
    }


def run_goodput_mode(seed: int) -> dict:
    """The workload telemetry plane under seeded pathology
    (BENCH_CP_MODES=goodput, ISSUE 15): a hollow fleet runs batch + serve
    while one seeded job suffers an input-pipeline stall and one gang
    hosts a seeded straggler worker; a node drain checkpoint-migrates a
    third gang. Asserted:

    - the stall job's dominant bucket reads ``input`` in its telemetry;
    - the ``goodput-collapse`` burn-rate alert FIRES within its
      documented bound (fast_long + 2 evaluation periods, at the bench's
      compressed window scale) of the gauge first crossing the floor,
      and CLEARS after the stall heals;
    - the Straggler Event names the exact pod and node;
    - ``restart_to_first_step_seconds`` records at least one planned
      MIGRATION outage span (the ROADMAP item 5 baseline).
    """
    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.api.client import TPUJobClient, TPUServeClient
    from mpi_operator_tpu.api.types import ALERT_NAMESPACE
    from mpi_operator_tpu.controller.disruption import DrainController
    from mpi_operator_tpu.controller.goodput import GoodputAggregator
    from mpi_operator_tpu.controller.serve import TPUServeController
    from mpi_operator_tpu.controller.slo_monitor import (
        SLOMonitor,
        load_slo_config,
    )
    from mpi_operator_tpu.executor.hollow import (
        HollowFleet,
        HollowTimeline,
        ServeLoadModel,
        TrainLoadModel,
    )
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.machinery.telemetry import ScrapeTarget
    from mpi_operator_tpu.opshell import metrics

    window_scale = 1.0 / 300.0  # fast (1s, 12s), slow (6s, 72s), hold 1s
    slo_cfg = load_slo_config().scaled(window_scale)
    floor = slo_cfg.objective("goodput-collapse").bound
    monitor_interval = 0.25
    # the DOCUMENTED detection bound (slo_defaults.json): fast_long + two
    # evaluation periods, measured from the gauge first crossing the floor
    detect_bound_s = slo_cfg.policy.fast[1] + 2 * monitor_interval

    store = ObjectStore()
    recorder = EventRecorder(store)
    train = TrainLoadModel(step_ms=40.0, compile_s=0.4, seed=seed)
    train.set_straggler("bench/skew-worker-1", 2.5)
    load = ServeLoadModel(capacity_qps=100.0)
    load.set_offered("bench/svc", 40.0)
    fleet = HollowFleet(
        store, 6,
        timeline=HollowTimeline(
            run_s=600.0, seed=seed, train=train,
            train_stats_interval_s=0.2,
            serve_warmup_s=0.3, serve_stats_interval_s=0.5, load=load,
        ),
        capacity_chips=8, heartbeat_interval=0.5,
    )
    controller = TPUJobController(store, recorder,
                                  ControllerOptions(threadiness=2))
    serve_ctrl = TPUServeController(store, recorder)
    scheduler = GangScheduler(store, recorder)
    drain = DrainController(store, recorder, interval=0.2)
    agg = GoodputAggregator(store, recorder, interval=0.25)
    monitor = SLOMonitor(store, [ScrapeTarget("bench", "self")], slo_cfg,
                         interval=monitor_interval)
    job_keys = [f"bench/{n}" for n in ("stall", "skew", "mig")]
    mig_before = metrics.restart_to_first_step.count(kind="migration")
    out: Dict[str, Any] = {"metric": "controlplane_goodput", "seed": seed,
                           "ok": False}
    t0 = time.time()
    try:
        controller.run()
        serve_ctrl.run()
        scheduler.start()
        fleet.start()
        drain.start()
        agg.start()
        jc = TPUJobClient(store, namespace="bench")
        for name, workers in (("stall", 2), ("skew", 3), ("mig", 2)):
            jc.create({
                "kind": "TPUJob", "metadata": {"name": name,
                                               "namespace": "bench"},
                "spec": {
                    "slice": {"accelerator": "cpu", "chips_per_host": 1},
                    "worker": {"replicas": workers, "template": {
                        "containers": [{"image": "x",
                                        "command": ["train"]}]}},
                },
            })
        TPUServeClient(store, namespace="bench").create({
            "kind": "TPUServe",
            "metadata": {"name": "svc", "namespace": "bench"},
            "spec": {"replicas": 1, "workers_per_replica": 1,
                     "slice": {"accelerator": "cpu", "chips_per_host": 2}},
        })

        def telemetry(name):
            job = store.try_get("TPUJob", "bench", name)
            return (job.status.train_telemetry or {}) if job else {}

        def wait_for(pred, timeout, what):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return True
                time.sleep(0.1)
            raise RuntimeError(f"timed out waiting for {what}")

        # --- phase 1: everything healthy and reporting. The monitor
        # starts only once the fleet is past warmup: at the bench's
        # 300x-compressed windows a job's first seconds (compile, no
        # steps yet) dominate fast_long the way they never could at the
        # production 1h window — starting the scrape on a live healthy
        # fleet is also the deployment-normal shape ---
        wait_for(lambda: all(telemetry(n).get("steps", 0) > 0
                             and (telemetry(n).get("goodput") or 0) > floor
                             for n in ("stall", "skew", "mig")),
                 30.0, "all jobs reporting healthy telemetry")
        monitor.start()
        alert_obj = lambda: store.try_get(  # noqa: E731
            "Alert", ALERT_NAMESPACE, "goodput-collapse")
        time.sleep(2.0)  # healthy baseline: no false positive
        a = alert_obj()
        out["false_positive"] = bool(a is not None and a.is_firing())

        # --- phase 2: the seeded input-pipeline stall ---
        train.set_stall("bench/stall", "input", 0.9)
        wait_for(lambda: metrics.job_goodput_ratio.get(
            job="bench/stall") < floor, 30.0, "goodput below the floor")
        breach_at = time.time()
        wait_for(lambda: (a := alert_obj()) is not None and a.is_firing(),
                 detect_bound_s + 5.0, "goodput-collapse firing")
        fired_at = time.time()
        out["detect_s"] = round(fired_at - breach_at, 2)
        out["detect_bound_s"] = round(detect_bound_s, 2)
        out["dominant_stall"] = telemetry("stall").get("dominant_stall")
        out["stall_goodput"] = telemetry("stall").get("goodput")

        # --- phase 3: heal; the alert must clear ---
        train.clear_stall("bench/stall")
        wait_for(lambda: (a := alert_obj()) is not None
                 and not a.is_firing(), 60.0, "goodput-collapse clearing")
        out["clear_s"] = round(time.time() - fired_at, 2)

        # --- the straggler (seeded from t=0) ---
        strag = telemetry("skew").get("straggler", "")
        pod = store.try_get("Pod", "bench", "skew-worker-1")
        node = pod.spec.node_name if pod else ""
        evs = [e for e in store.list("Event")
               if e.reason == "Straggler" and "skew-worker-1" in e.message
               and node and node in e.message]
        out["straggler"] = strag
        out["straggler_event"] = bool(evs)

        # --- phase 4: drain the node hosting mig's coordinator ---
        mig_pod = store.get("Pod", "bench", "mig-worker-0")
        victim = mig_pod.spec.node_name
        out["drained_node"] = victim
        fleet.announce_maintenance(victim, time.time() + 20.0)
        wait_for(
            lambda: metrics.restart_to_first_step.count(
                kind="migration") > mig_before,
            40.0, "restart_to_first_step recorded for the migration",
        )
        snap = metrics.restart_to_first_step.snapshot(kind="migration")
        # mean outage span of this run's migrations (sum/count delta is
        # overkill for one seeded migration; count delta asserted above)
        out["restart_to_first_step_count"] = int(
            metrics.restart_to_first_step.count(kind="migration")
            - mig_before)
        out["restart_to_first_step_p50_s"] = round(
            metrics.histogram_quantile(0.5, snap), 2)
        wait_for(lambda: not cond.is_finished(
            store.get("TPUJob", "bench", "mig").status)
            and telemetry("mig").get("steps", 0) > 0,
            20.0, "migrated gang stepping again")
        out["mig_generation"] = store.get(
            "TPUJob", "bench", "mig").status.restart_generation
        out["mig_restart_count"] = store.get(
            "TPUJob", "bench", "mig").status.restart_count

        out["elapsed_s"] = round(time.time() - t0, 1)
        out["ok"] = bool(
            not out["false_positive"]
            and out["dominant_stall"] == "input"
            and out["detect_s"] <= detect_bound_s
            and strag.startswith("bench/skew-worker-1@")
            and out["straggler_event"]
            and out["restart_to_first_step_count"] >= 1
            and out["mig_generation"] >= 1
            and out["mig_restart_count"] == 0  # the migration was FREE
        )
        return out
    finally:
        monitor.stop()
        agg.stop()
        drain.stop()
        scheduler.stop()
        serve_ctrl.stop()
        controller.stop()
        fleet.stop()
        # the registry is process-global and this mode runs TWICE: run 1's
        # per-job gauges must not leak a stale collapsed value into run
        # 2's scrape (a counter-reset false alert)
        for key in job_keys:
            metrics.job_goodput_ratio.remove(job=key)
            metrics.job_stragglers.remove(job=key)


def run_goodput_llama() -> dict:
    """The REAL (non-hollow) half of the goodput acceptance: a short
    llama gang on the local executor with stepstats enabled end to end —
    train_stats mirrored into pod status, measured stepstats overhead
    <= 2% of step p50, and the `ctl profile` round trip (stamp → workers
    capture a jax.profiler trace → --status → --fetch rc=0)."""
    import io
    import contextlib
    import shutil

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.controller.goodput import GoodputAggregator
    from mpi_operator_tpu.executor.local import LocalExecutor
    from mpi_operator_tpu.opshell import ctl
    from mpi_operator_tpu.runtime.stepstats import StepStatsRecorder

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="bench-goodput-llama-")
    ckpt = os.path.join(tmp, "ckpt")
    db = os.path.join(tmp, "store.db")
    store = SqliteStore(db, poll_interval=0.02)
    spec = f"sqlite:{db}"
    recorder = EventRecorder(store)
    controller = TPUJobController(store, recorder,
                                  ControllerOptions(threadiness=2))
    scheduler = GangScheduler(store, recorder)
    executor = LocalExecutor(store, workdir=repo, require_binding=True,
                             stepstats_poll=0.5)
    agg = GoodputAggregator(store, recorder, interval=0.5)
    out: Dict[str, Any] = {"metric": "goodput_llama", "ok": False}
    t0 = time.time()
    steps_total = int(os.environ.get("BENCH_CP_GOODPUT_LLAMA_STEPS", "80"))
    try:
        controller.run()
        scheduler.start()
        executor.start()
        agg.start()
        jc = TPUJobClient(store)
        jc.create({
            "kind": "TPUJob", "metadata": {"name": "llama"},
            "spec": {
                "slice": {"accelerator": "cpu", "chips_per_host": 1},
                "run_policy": {"backoff_limit": 2},
                "worker": {
                    "replicas": 2, "restart_policy": "ExitCode",
                    "template": {"containers": [{
                        "image": "local",
                        "command": ["python", "examples/llama_worker.py"],
                        "env": [
                            {"name": "LLAMA_CONFIG", "value": "tiny"},
                            {"name": "LLAMA_BATCH", "value": "2"},
                            {"name": "LLAMA_SEQ", "value": "32"},
                            {"name": "LLAMA_STEPS",
                             "value": str(steps_total)},
                            {"name": "LLAMA_CKPT", "value": ckpt},
                            {"name": "LLAMA_SAVE_EVERY", "value": "40"},
                            {"name": "LLAMA_CHECK_EVERY", "value": "5"},
                            {"name": "LLAMA_STEP_SLEEP", "value": "0.05"},
                        ],
                    }]},
                },
            },
        })

        def coord_stats():
            p = store.try_get("Pod", "default", "llama-worker-0")
            return (p.status.train_stats or {}) if p else {}

        def wait_for(pred, timeout, what):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if pred():
                    return True
                time.sleep(0.25)
            raise RuntimeError(f"timed out waiting for {what}")

        # real training is stepping AND its stats are mirrored
        wait_for(lambda: coord_stats().get("steps", 0) >= 5, 180.0,
                 "llama train_stats in pod status")

        # --- the profile round trip, through the REAL ctl verbs ---
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = ctl.main(["--store", spec, "profile", "llama",
                           "--steps", "3"])
        out["profile_request_rc"] = rc

        def profile_done():
            with contextlib.redirect_stdout(io.StringIO()):
                return ctl.main(["--store", spec, "profile", "llama",
                                 "--status"]) == 0
        wait_for(profile_done, 120.0, "profile capture acked done")
        prof = coord_stats().get("profile") or {}
        trace_files = []
        if prof.get("dir") and os.path.isdir(prof["dir"]):
            for root, _dirs, files in os.walk(prof["dir"]):
                trace_files += [os.path.join(root, f) for f in files]
        out["trace_files"] = len(trace_files)
        dest = os.path.join(tmp, "fetched")
        with contextlib.redirect_stdout(io.StringIO()):
            out["profile_fetch_rc"] = ctl.main([
                "--store", spec, "profile", "llama", "--fetch",
                "--dest", dest])
        out["fetched_files"] = sum(
            len(fs) for _r, _d, fs in os.walk(dest))

        # the gang must still FINISH (profiling never perturbs outcome)
        wait_for(lambda: cond.is_finished(
            store.get("TPUJob", "default", "llama").status), 240.0,
            "llama job finishing")
        job = store.get("TPUJob", "default", "llama")
        out["succeeded"] = cond.is_succeeded(job.status)
        tel = job.status.train_telemetry or {}
        out["goodput"] = tel.get("goodput")
        out["buckets"] = tel.get("buckets")
        step_p50_ms = float(coord_stats().get("step_p50_ms", 0.0) or 0.0)
        out["step_p50_ms"] = step_p50_ms

        # --- stepstats overhead: the measured per-step recorder cost
        # (the exact call sequence the elastic loop pays: three phases +
        # step_done, flush cadence included) against the REAL step p50 ---
        rec = StepStatsRecorder(os.path.join(tmp, "bench.stats.json"),
                                interval=1.0)
        n = 4000
        t_bench = time.perf_counter()
        for i in range(n):
            with rec.phase("input"):
                pass
            with rec.phase("compute"):
                pass
            with rec.phase("sync"):
                pass
            rec.step_done(i)
        per_step_us = (time.perf_counter() - t_bench) / n * 1e6
        out["stepstats_cost_us_per_step"] = round(per_step_us, 1)
        out["stepstats_overhead_pct"] = round(
            per_step_us / 1e3 / max(1e-9, step_p50_ms) * 100.0, 3)

        out["elapsed_s"] = round(time.time() - t0, 1)
        out["ok"] = bool(
            out["succeeded"]
            and out["profile_request_rc"] == 0
            and out["trace_files"] > 0
            and out["profile_fetch_rc"] == 0
            and out["fetched_files"] > 0
            and step_p50_ms > 0
            and out["stepstats_overhead_pct"] <= 2.0
            and (out["goodput"] or 0) > 0
        )
        return out
    finally:
        agg.stop()
        executor.stop()
        scheduler.stop()
        controller.stop()
        store.close()
        shutil.rmtree(tmp, ignore_errors=True)


def run_slo_overhead(jobs: int, pods: int, rounds: int) -> dict:
    """The monitor-tax bound (half of BENCH_CP_MODES=slo): interleaved
    off/on informer reconcile storms — 'on' runs a live SLOMonitor at a
    DENSE 0.25s scrape+evaluate cadence against this process's real
    /metrics endpoint (the registry the storm's controller is writing
    into), 60× denser than the production 15s default — best-of-two per
    mode so run-to-run drift cancels. Acceptance (ISSUE 13): the
    monitor's scrape overhead stays ≤2% of reconcile p50 even at that
    cadence."""
    from mpi_operator_tpu.controller.slo_monitor import (
        SLOMonitor,
        load_slo_config,
    )
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.machinery.telemetry import ScrapeTarget
    from mpi_operator_tpu.opshell.server import OpsServer

    ops = OpsServer(0)
    ops.start()
    results = {"off": [], "on": []}
    monitor = None
    try:
        for _ in range(2):
            results["off"].append(run_mode("informer", jobs, pods, rounds))
            monitor = SLOMonitor(
                ObjectStore(),
                [ScrapeTarget("operator",
                              f"http://127.0.0.1:{ops.port}/metrics")],
                load_slo_config(), interval=0.25,
            ).start()
            try:
                results["on"].append(
                    run_mode("informer", jobs, pods, rounds))
            finally:
                monitor.stop()
    finally:
        ops.stop()
    off = min(results["off"], key=lambda r: r["sync_p50_ms"])
    on = min(results["on"], key=lambda r: r["sync_p50_ms"])
    pct = round((on["sync_p50_ms"] - off["sync_p50_ms"])
                / max(1e-9, off["sync_p50_ms"]) * 100.0, 1)
    return {
        "metric": "controlplane_slo_overhead",
        "jobs": jobs, "pods_per_job": pods, "rounds": rounds,
        "scrape_interval_s": 0.25,
        "sync_p50_ms_monitor_off": off["sync_p50_ms"],
        "sync_p50_ms_monitor_on": on["sync_p50_ms"],
        "p50_overhead_pct": pct,
        "overhead_ok": bool(pct <= 2.0),
    }


def run_slo_detection(seed: int) -> dict:
    """The detection e2e (BENCH_CP_MODES=slo, ISSUE 13): the deployed
    shape — a `tpu-store` process and a hollow-fleet process (both
    exporting /metrics via --monitoring-port), the operator plane in
    THIS process behind a ChaosProxy on its store seam, and the SLO
    monitor scraping all three over real HTTP with compressed burn
    windows (scale 1/600: fast 0.5s/6s, slow 3s/36s).

    A seeded chaos fault — 0.6s injected store latency for 10s, the
    'store seam degraded' incident — must blow the reconcile-latency
    objective past its 1s good-event bound; the bar:

    - NO false positive during the clean baseline;
    - the matching alert FIRES within the documented detection bound
      (fast_long + 2 evaluation periods + scrape slack);
    - it CLEARS after the heal within the clear bound (windows drain +
      clean hold);
    - the firing carries a flight-recorder bundle and
      `ctl trace --last-incident` renders it rc=0.

    The caller runs this TWICE on one seed (chaos determinism)."""
    import io
    import contextlib
    import shutil
    import subprocess
    import threading

    from mpi_operator_tpu.controller.slo_monitor import (
        SLOMonitor,
        load_slo_config,
    )
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.machinery.chaos import (
        ChaosController,
        ChaosProxy,
        ChaosScript,
    )
    from mpi_operator_tpu.machinery.replica_wire import free_ports
    from mpi_operator_tpu.machinery.telemetry import ScrapeTarget
    from mpi_operator_tpu.opshell import ctl
    from mpi_operator_tpu.opshell.server import OpsServer

    nodes = int(os.environ.get("BENCH_CP_SLO_NODES", "16"))
    fault_s = float(os.environ.get("BENCH_CP_SLO_FAULT_S", "10"))
    delay_s = 0.6
    scale = 1.0 / 600.0
    config = load_slo_config().scaled(scale)
    interval = 0.25
    # the documented detection-latency bound: the fast pair's LONG
    # window must fill past the burn threshold, plus two evaluation
    # periods and scrape slack
    detect_bound_s = config.policy.fast[1] + 2 * interval + 2.0
    # the clear bound: every window drains the breach, then the hold
    clear_bound_s = (config.policy.slow[1] + config.policy.clear_hold_s
                     + 2 * interval + 4.0)

    tmp = tempfile.mkdtemp(prefix="bench-cp-slo-")
    trace_dir = os.path.join(tmp, "traces")
    incident_dir = os.path.join(tmp, "incidents")
    trace.TRACER.configure("bench-slo", dir=trace_dir)
    ports = free_ports(3)
    store_port, store_mon, fleet_mon = ports
    store_url = f"http://127.0.0.1:{store_port}"
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.abspath(__file__)), TPUJOB_TRACE_DIR=trace_dir)
    out: dict = {"metric": "controlplane_slo_detection", "seed": seed,
                 "nodes": nodes, "ok": False,
                 "detect_bound_s": round(detect_bound_s, 1),
                 "clear_bound_s": round(clear_bound_s, 1)}
    store_proc = fleet_proc = None
    proxy = None
    monitor = None
    cache = controller = None
    ops = None
    clients = []
    stop = threading.Event()
    try:
        store_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
             "--store", f"sqlite:{os.path.join(tmp, 'store.db')}",
             "--listen", f"127.0.0.1:{store_port}",
             "--log-capacity", "16384",
             "--monitoring-port", str(store_mon)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, "store.log"), "w"),
        )
        direct = HttpStoreClient(store_url, timeout=30.0,
                                 conn_refused_retries=20,
                                 watch_poll_timeout=2.0)
        clients.append(direct)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                direct.list("Node")
                break
            except Exception:
                time.sleep(0.2)
        fleet_proc = subprocess.Popen(
            [sys.executable, "-m", "mpi_operator_tpu.executor.hollow",
             "--store", store_url, "--nodes", str(nodes),
             "--chips", "8", "--run-s", "0.2", "--heartbeat", "5",
             "--seed", str(seed), "--monitoring-port", str(fleet_mon)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=open(os.path.join(tmp, "fleet.log"), "w"),
        )
        # the operator plane reaches the store THROUGH the chaos seam:
        # injected latency lands exactly where a degraded store would
        proxy = ChaosProxy(store_url, seed=seed).start()
        pclient = HttpStoreClient(proxy.url, timeout=60.0,
                                  conn_refused_retries=20,
                                  watch_poll_timeout=2.0)
        clients.append(pclient)
        cache = InformerCache(pclient).start()
        if not cache.wait_for_sync(30.0):
            raise RuntimeError("informer cache never synced")
        recorder = EventRecorder(pclient)
        controller = TPUJobController(
            pclient, recorder, ControllerOptions(threadiness=4),
            cache=cache)
        scheduler = GangScheduler(pclient, recorder, cache=cache)
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(cache.list("Node")) >= nodes:
                break
            time.sleep(0.2)
        controller.run()

        def sched_loop():
            while not stop.is_set():
                try:
                    scheduler.sync()
                except Exception:
                    pass  # transient conflicts; next pass heals
                stop.wait(0.2)

        threading.Thread(target=sched_loop, daemon=True).start()

        # a steady job stream keeps reconcile events flowing through
        # every phase (burn windows need event mass to judge)
        submitted = [0]

        def job_stream():
            while not stop.is_set():
                try:
                    direct.create(_make_job(submitted[0], 1, clean="All"))
                    submitted[0] += 1
                except Exception:
                    pass  # the stream is load, not the bar
                stop.wait(0.3)

        threading.Thread(target=job_stream, daemon=True).start()

        # the monitor: scrapes operator (this process, over real HTTP),
        # store, fleet; writes alerts through the DIRECT client — the
        # alerting plane must not ride the seam it is alerting about
        ops = OpsServer(0)
        ops.start()
        monitor = SLOMonitor(
            direct,
            [ScrapeTarget("operator",
                          f"http://127.0.0.1:{ops.port}/metrics"),
             ScrapeTarget("store",
                          f"http://127.0.0.1:{store_mon}/metrics"),
             ScrapeTarget("fleet",
                          f"http://127.0.0.1:{fleet_mon}/metrics")],
            config, interval=interval, incident_dir=incident_dir,
        ).start()

        def firing_alerts():
            try:
                return sorted(
                    a.metadata.name for a in direct.list(
                        "Alert", "monitoring")
                    if a.is_firing()
                )
            except Exception:
                return []

        # --- clean baseline: no false positives --------------------------
        baseline_s = max(8.0, config.policy.slow[0] + 2.0)
        t0 = time.time()
        false_positives = set()
        while time.time() - t0 < baseline_s:
            false_positives.update(firing_alerts())
            time.sleep(0.5)
        out["false_positives"] = sorted(false_positives)

        # --- the seeded fault --------------------------------------------
        script = ChaosScript.parse({
            "seed": seed,
            "actions": [{"at": 0.0, "fault": "delay",
                         "seconds": delay_s, "duration": fault_s}],
        })
        fault_at = time.time()
        chaos = ChaosController(script, proxy=proxy).arm()
        fired_at = None
        deadline = fault_at + detect_bound_s + 2.0
        while time.time() < deadline and fired_at is None:
            if "reconcile-latency" in firing_alerts():
                fired_at = time.time()
            time.sleep(0.2)
        chaos.join(5.0)
        out["fired"] = fired_at is not None
        out["detection_s"] = (round(fired_at - fault_at, 2)
                              if fired_at else None)
        out["also_firing"] = [n for n in firing_alerts()
                              if n != "reconcile-latency"]
        if fired_at is None:
            out["error"] = "alert never fired"
            return out
        alert = direct.get("Alert", "monitoring", "reconcile-latency")
        out["window"] = alert.status.window
        out["burn"] = alert.status.burn
        bundle = alert.status.incident
        out["bundle_ok"] = bool(bundle and os.path.exists(bundle))

        # --- heal: the alert must clear --------------------------------
        heal_at = fault_at + fault_s
        resolved_at = None
        deadline = heal_at + clear_bound_s + 10.0
        while time.time() < deadline and resolved_at is None:
            if "reconcile-latency" not in firing_alerts():
                a = direct.get("Alert", "monitoring", "reconcile-latency")
                if a.status.state == "Resolved":
                    resolved_at = time.time()
                    break
            time.sleep(0.25)
        out["resolved"] = resolved_at is not None
        out["clear_s"] = (round(resolved_at - heal_at, 2)
                          if resolved_at else None)

        # --- ctl renders the incident (bundle linked) rc=0 --------------
        trace.TRACER.flush()
        old_inc = os.environ.get("TPUJOB_INCIDENT_DIR")
        os.environ["TPUJOB_INCIDENT_DIR"] = incident_dir
        try:
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = ctl.main(["--store", store_url, "trace",
                               "--last-incident", "--trace-dir", trace_dir])
        finally:
            if old_inc is None:
                os.environ.pop("TPUJOB_INCIDENT_DIR", None)
            else:
                os.environ["TPUJOB_INCIDENT_DIR"] = old_inc
        out["ctl_trace_rc"] = rc
        out["ctl_trace_links_bundle"] = "incident bundle:" in buf.getvalue()
        out["jobs_submitted"] = submitted[0]
        out["ok"] = bool(
            not false_positives
            and out["fired"]
            and out["detection_s"] <= detect_bound_s
            and out["bundle_ok"]
            and out["resolved"]
            and out["clear_s"] <= clear_bound_s
            and rc == 0
            and out["ctl_trace_links_bundle"]
        )
        return out
    finally:
        stop.set()
        if monitor is not None:
            monitor.stop()
        if controller is not None:
            controller.stop()
        if cache is not None:
            cache.stop()
        if ops is not None:
            ops.stop()
        for c in clients:
            c.close()
        if proxy is not None:
            proxy.stop()
        for proc in (fleet_proc, store_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        trace.TRACER.disable()
        if os.environ.get("BENCH_CP_SLO_KEEP"):
            print(f"slo dir kept: {tmp}", file=sys.stderr)
        else:
            shutil.rmtree(tmp, ignore_errors=True)


def run_fanout_mode() -> dict:
    """The O(events) fan-out proof (BENCH_CP_MODES=fanout): a fixed event
    stream delivered to 10 vs ``BENCH_CP_FANOUT_WATCHERS`` (default 500)
    long-poll watchers, with the per-event wire bytes PREENCODED at append
    (the new path) vs re-encoded per watcher (preencode=False, the old
    path). Measured: server-side encode+assembly wall time from
    http_store.watch_encode_stats. Acceptance: growing watchers 10→500
    raises the preencoded cost <2× while the legacy path grows ~linearly
    with watchers (~50×)."""
    import json as _json
    import threading
    import urllib.request

    from mpi_operator_tpu.machinery.http_store import (
        reset_watch_encode_stats,
        watch_encode_stats,
    )
    from mpi_operator_tpu.machinery.objects import Pod
    from mpi_operator_tpu.machinery.store import ObjectStore

    events = int(os.environ.get("BENCH_CP_FANOUT_EVENTS", "200"))
    big = int(os.environ.get("BENCH_CP_FANOUT_WATCHERS", "500"))

    def drive(preencode: bool, watchers: int) -> dict:
        server = StoreServer(ObjectStore(), "127.0.0.1", 0,
                             log_capacity=events * 2 + 64,
                             preencode=preencode).start()
        stop = threading.Event()
        seen = [0] * watchers
        registered = [False] * watchers

        def watcher(i: int) -> None:
            base = f"http://127.0.0.1:{server.port}/v1/watch"
            try:
                with urllib.request.urlopen(base + "?after=-1",
                                            timeout=30) as r:
                    reg = _json.loads(r.read())
                cursor, inst = reg["next"], reg["instance"]
                registered[i] = True
                while not stop.is_set() and seen[i] < events:
                    with urllib.request.urlopen(
                        f"{base}?after={cursor}&timeout=5&instance={inst}",
                        timeout=20,
                    ) as r:
                        payload = _json.loads(r.read())
                    cursor = payload.get("next", cursor)
                    seen[i] += len(payload.get("events", []))
            except Exception:
                registered[i] = True  # do not wedge the start barrier
                # a dead watcher just stops counting

        threads = [threading.Thread(target=watcher, args=(i,), daemon=True)
                   for i in range(watchers)]
        for t in threads:
            t.start()
        # every watcher must be REGISTERED before the event stream starts:
        # registration hands the current head, so late registrants would
        # silently miss early events and the drain below would never end
        deadline = time.time() + 60
        while time.time() < deadline and not all(registered):
            time.sleep(0.05)
        reset_watch_encode_stats()
        cpu0 = time.process_time()
        writer = HttpStoreClient(server.url, timeout=30.0)
        for i in range(events):
            writer.create(Pod(metadata=ObjectMeta(
                name=f"f-{i:05d}", namespace="bench")))
        # drain until everyone saw everything, or delivery plateaus
        deadline = time.time() + 60 + watchers * 0.1
        last_total, last_change = -1, time.time()
        while time.time() < deadline and min(seen) < events:
            total = sum(seen)
            if total != last_total:
                last_total, last_change = total, time.time()
            elif time.time() - last_change > 10.0:
                break  # plateaued (some watcher died); report what landed
            time.sleep(0.05)
        stats = watch_encode_stats()
        cpu = time.process_time() - cpu0
        stop.set()
        writer.close()
        server.stop()
        for t in threads:
            t.join(timeout=2.0)
        return {
            "watchers": watchers,
            "delivered_min": min(seen),
            "encode_s": round(stats["encode_s"], 4),
            "assembly_s": round(stats["assembly_s"], 4),
            "events_encoded": stats["events_encoded"],
            "payloads": stats["payloads"],
            "process_cpu_s": round(cpu, 3),
        }

    out = {"metric": "controlplane_watch_fanout", "events": events}
    for label, pre in (("preencoded", True), ("reencode", False)):
        small = drive(pre, 10)
        large = drive(pre, big)
        ratio = large["encode_s"] / max(1e-9, small["encode_s"])
        out[label] = {
            "w10": small, f"w{big}": large,
            "encode_cost_ratio": round(ratio, 2),
        }
    out["fanout_is_o_events"] = bool(
        out["preencoded"]["encode_cost_ratio"] < 2.0
    )
    return out


def main() -> None:
    jobs = int(os.environ.get("BENCH_CP_JOBS", "200"))
    pods = int(os.environ.get("BENCH_CP_PODS", "8"))
    rounds = int(os.environ.get("BENCH_CP_ROUNDS", "3"))
    agents = int(os.environ.get("BENCH_CP_AGENTS", "16"))
    writes = int(os.environ.get("BENCH_CP_WRITES", "400"))
    modes = os.environ.get("BENCH_CP_MODES", "store,informer").split(",")
    results = {}
    for mode in modes:
        mode = mode.strip()
        if mode == "write":
            r = run_write_mode(jobs, pods, agents)
        elif mode == "replica":
            r = run_replica_mode(writes)
        elif mode == "hist":
            r = run_hist_mode(writes)
        elif mode == "traceoverhead":
            r = run_trace_overhead(jobs, pods, rounds)
        elif mode == "scale":
            r = run_scale_mode(
                int(os.environ.get("BENCH_CP_SCALE_NODES", "1000")),
                int(os.environ.get("BENCH_CP_SCALE_JOBS", "10000")),
                int(os.environ.get("BENCH_CP_SCALE_PODS", "1")),
            )
        elif mode == "torture":
            # TWO runs on ONE seed: the chaos determinism contract — the
            # bar must hold both times, not once by luck
            seed = int(os.environ.get("BENCH_CP_TORTURE_SEED", "1207"))
            nodes_t = int(os.environ.get("BENCH_CP_TORTURE_NODES", "100"))
            jobs_t = int(os.environ.get("BENCH_CP_TORTURE_JOBS", "500"))
            runs = [
                run_torture_mode(nodes_t, jobs_t, 1, seed)
                for _ in range(int(os.environ.get(
                    "BENCH_CP_TORTURE_RUNS", "2")))
            ]
            r = {
                "metric": "controlplane_torture",
                "seed": seed,
                "runs": runs,
                "ok": all(x.get("ok") for x in runs),
            }
        elif mode == "soak":
            # the whole A/B TWICE on ONE seed (scenario determinism):
            # the compressed day's bar must hold both times, not once by
            # luck (ISSUE 18 acceptance)
            seed = int(os.environ.get("BENCH_CP_SOAK_SEED", "1807"))
            runs = [
                run_soak_mode(seed)
                for _ in range(int(os.environ.get("BENCH_CP_SOAK_RUNS",
                                                  "2")))
            ]
            r = {
                "metric": "controlplane_soak",
                "seed": seed,
                "runs": runs,
                "ok": all(x.get("ok") for x in runs),
            }
        elif mode == "serve":
            r = run_serve_mode()
        elif mode == "drain":
            # TWO runs on ONE seed (the chaos determinism contract): the
            # rolling-maintenance bar must hold both times, not once by
            # luck (ISSUE 14 acceptance → BENCH_CP_r14.json)
            seed = int(os.environ.get("BENCH_CP_DRAIN_SEED", "1407"))
            runs = [
                run_drain_mode(seed)
                for _ in range(int(os.environ.get("BENCH_CP_DRAIN_RUNS",
                                                  "2")))
            ]
            r = {
                "metric": "controlplane_drain",
                "seed": seed,
                "runs": runs,
                "ok": all(x.get("ok") for x in runs),
            }
        elif mode == "slo":
            # TWO detection runs on ONE seed (chaos determinism) + the
            # monitor-overhead A/B, one verdict (ISSUE 13 acceptance)
            seed = int(os.environ.get("BENCH_CP_SLO_SEED", "1307"))
            overhead = run_slo_overhead(
                int(os.environ.get("BENCH_CP_SLO_OVERHEAD_JOBS", "100")),
                4, 2)
            runs = [
                run_slo_detection(seed)
                for _ in range(int(os.environ.get("BENCH_CP_SLO_RUNS",
                                                  "2")))
            ]
            r = {
                "metric": "controlplane_slo",
                "seed": seed,
                "overhead": overhead,
                "runs": runs,
                "ok": bool(overhead["overhead_ok"]
                           and all(x.get("ok") for x in runs)),
            }
        elif mode == "goodput":
            # TWO seeded hollow runs (the chaos determinism contract) +
            # ONE real llama run (overhead + profile round trip), one
            # verdict (ISSUE 15 acceptance → BENCH_CP_r15.json)
            seed = int(os.environ.get("BENCH_CP_GOODPUT_SEED", "1507"))
            runs = [
                run_goodput_mode(seed)
                for _ in range(int(os.environ.get(
                    "BENCH_CP_GOODPUT_RUNS", "2")))
            ]
            llama = run_goodput_llama()
            r = {
                "metric": "controlplane_goodput",
                "seed": seed,
                "runs": runs,
                "llama": llama,
                "ok": bool(all(x.get("ok") for x in runs)
                           and llama.get("ok")),
            }
        elif mode == "fanout":
            r = run_fanout_mode()
        else:
            r = run_mode(mode, jobs, pods, rounds)
        results[mode] = r
        print(json.dumps(r), flush=True)
    if "store" in results and "informer" in results:
        s, i = results["store"], results["informer"]
        print(json.dumps({
            "metric": "controlplane_informer_speedup",
            "jobs": jobs,
            "pods_per_job": pods,
            "p50_speedup": round(
                s["sync_p50_ms"] / max(1e-9, i["sync_p50_ms"]), 2
            ),
            "p99_speedup": round(
                s["sync_p99_ms"] / max(1e-9, i["sync_p99_ms"]), 2
            ),
            "read_qps_store_mode": s["store_read_qps"],
            "read_qps_informer_mode": i["store_read_qps"],
        }), flush=True)


if __name__ == "__main__":
    main()
