"""Control-plane benchmark: reconcile storm against the sqlite-backed HTTP
store, with and without the informer cache (machinery/cache.py).

The metric the informer/lister subsystem exists to move: before it, every
reconcile issued full ``store.list``/``get`` round-trips — over HTTP in the
distributed deployment — so store read load scaled as
O(jobs × pods × resyncs). With listers, steady-state controller reads come
from the watch-fed cache and the store sees only writes plus one long-poll.

Shape: N synthetic TPUJobs × M workers each (default 200 × 8 — the ISSUE 1
acceptance point) are created through a real HttpStoreClient against a real
StoreServer backed by SqliteStore. The controller converges them (service,
configmap, podgroup, workers, status), the gang scheduler binds every gang,
and then a steady-state storm re-reconciles every job for R rounds while
measuring per-sync latency and the server's read counters. Run it via::

  python bench_controlplane.py                      # both modes + compare
  BENCH_MODEL=controlplane python bench.py          # same, no TPU work

Knobs: BENCH_CP_JOBS, BENCH_CP_PODS, BENCH_CP_ROUNDS, BENCH_CP_MODES
("store", "informer", or "store,informer"). No jax required — this is the
pure-python control plane.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mpi_operator_tpu.api.types import (  # noqa: E402
    Container,
    ObjectMeta,
    PodTemplate,
    ReplicaSpec,
    RunPolicy,
    SliceSpec,
    TPUJob,
    TPUJobSpec,
)
from mpi_operator_tpu.controller.controller import (  # noqa: E402
    ControllerOptions,
    TPUJobController,
)
from mpi_operator_tpu.machinery.cache import InformerCache  # noqa: E402
from mpi_operator_tpu.machinery.events import EventRecorder  # noqa: E402
from mpi_operator_tpu.machinery.http_store import (  # noqa: E402
    HttpStoreClient,
    StoreServer,
)
from mpi_operator_tpu.machinery.sqlite_store import SqliteStore  # noqa: E402
from mpi_operator_tpu.scheduler.gang import GangScheduler  # noqa: E402


def _make_job(i: int, pods: int) -> TPUJob:
    return TPUJob(
        metadata=ObjectMeta(name=f"storm-{i:04d}", namespace="bench"),
        spec=TPUJobSpec(
            slots_per_worker=1,
            run_policy=RunPolicy(clean_pod_policy="None"),
            worker=ReplicaSpec(
                replicas=pods,
                restart_policy="Never",
                template=PodTemplate(
                    container=Container(image="bench/noop", command=["true"])
                ),
            ),
            slice=SliceSpec(accelerator="cpu", chips_per_host=1),
        ),
    )


def _reads(stats: dict) -> int:
    """Store-side read requests: object gets + lists. Watch long-polls are
    reported separately — they are the informer's O(1) replacement, not the
    per-reconcile load this benchmark measures."""
    return stats.get("get", 0) + stats.get("list", 0)


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, max(0, int(round(p * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def run_mode(mode: str, jobs: int, pods: int, rounds: int) -> dict:
    """One full converge + storm in ``mode`` ('store' = direct reads,
    'informer' = lister reads) against a fresh sqlite-backed HTTP store."""
    tmp = tempfile.mkdtemp(prefix=f"bench-cp-{mode}-")
    backing = SqliteStore(os.path.join(tmp, "store.db"))
    server = StoreServer(backing, "127.0.0.1", 0).start()
    client = HttpStoreClient(server.url, timeout=30.0, watch_poll_timeout=5.0)
    cache = None
    try:
        if mode == "informer":
            cache = InformerCache(client).start()
            if not cache.wait_for_sync(30.0):
                raise RuntimeError("informer cache never synced")
        recorder = EventRecorder(client)
        controller = TPUJobController(
            client, recorder, ControllerOptions(threadiness=0), cache=cache
        )
        scheduler = GangScheduler(client, recorder, cache=cache)

        keys = []
        for i in range(jobs):
            job = client.create(_make_job(i, pods))
            keys.append(job.metadata.key())

        # converge: drive sync_handler + scheduler.sync directly (no worker
        # threads — deterministic measurement) until a full pass of syncs
        # succeeds twice; informer mode needs the watch to carry each pass's
        # writes back into the cache before the next pass settles
        t_conv = time.perf_counter()
        clean_passes = 0
        for _ in range(30):
            ok = all([controller.sync_handler(k) for k in keys])
            scheduler.sync()
            clean_passes = clean_passes + 1 if ok else 0
            if clean_passes >= 2:
                break
            if cache is not None:
                time.sleep(0.3)  # let the watch land this pass's writes
        converge_s = time.perf_counter() - t_conv
        if cache is not None:
            time.sleep(0.5)  # quiesce: cache observes the final writes

        # steady-state storm: every job re-reconciled, rounds times over
        stats0 = server.stats()
        lat = []
        t0 = time.perf_counter()
        for _ in range(rounds):
            for k in keys:
                t = time.perf_counter()
                controller.sync_handler(k)
                lat.append(time.perf_counter() - t)
            scheduler.sync()
        elapsed = time.perf_counter() - t0
        stats1 = server.stats()

        lat.sort()
        reads = _reads(stats1) - _reads(stats0)
        writes = sum(
            stats1.get(w, 0) - stats0.get(w, 0)
            for w in ("create", "update", "delete")
        )
        return {
            "metric": "controlplane_reconcile",
            "mode": mode,
            "jobs": jobs,
            "pods_per_job": pods,
            "rounds": rounds,
            "syncs": len(lat),
            "sync_p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
            "sync_p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
            "store_read_qps": round(reads / elapsed, 1),
            "store_reads_per_sync": round(reads / max(1, len(lat)), 2),
            "store_writes": writes,
            "watch_polls": stats1.get("watch", 0) - stats0.get("watch", 0),
            "storm_elapsed_s": round(elapsed, 2),
            "converge_s": round(converge_s, 2),
        }
    finally:
        if cache is not None:
            cache.stop()
        client.close()
        server.stop()
        backing.close()


def main() -> None:
    jobs = int(os.environ.get("BENCH_CP_JOBS", "200"))
    pods = int(os.environ.get("BENCH_CP_PODS", "8"))
    rounds = int(os.environ.get("BENCH_CP_ROUNDS", "3"))
    modes = os.environ.get("BENCH_CP_MODES", "store,informer").split(",")
    results = {}
    for mode in modes:
        mode = mode.strip()
        r = run_mode(mode, jobs, pods, rounds)
        results[mode] = r
        print(json.dumps(r), flush=True)
    if "store" in results and "informer" in results:
        s, i = results["store"], results["informer"]
        print(json.dumps({
            "metric": "controlplane_informer_speedup",
            "jobs": jobs,
            "pods_per_job": pods,
            "p50_speedup": round(
                s["sync_p50_ms"] / max(1e-9, i["sync_p50_ms"]), 2
            ),
            "p99_speedup": round(
                s["sync_p99_ms"] / max(1e-9, i["sync_p99_ms"]), 2
            ),
            "read_qps_store_mode": s["store_read_qps"],
            "read_qps_informer_mode": i["store_read_qps"],
        }), flush=True)


if __name__ == "__main__":
    main()
