"""Profile the llama bench step and dump per-HLO-op stats.

Dev tool (not part of the package): mirrors PERF.md's recipe — capture a
jax.profiler trace of the compiled train step, convert with xprof's
hlo_stats, and write /tmp/llama_hlo_stats.json for op-level analysis
(time by boundedness, per-fusion GFLOP/s). The workload comes from
bench.llama_setup so the profile measures exactly the step bench.py times.
Run on the TPU chip.
"""

import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import glob
import json

import jax

from bench import llama_per_chip_batch, llama_setup


def main():
    per_chip_batch = llama_per_chip_batch()
    seq_len = int(os.environ.get("BENCH_SEQ", "2048"))
    _, trainer, state, batch, _ = llama_setup(per_chip_batch, seq_len)

    for _ in range(3):
        state, m = trainer.train_step(state, batch)
    jax.block_until_ready(m["loss"])

    logdir = "/tmp/llama_profile"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        for _ in range(5):
            state, m = trainer.train_step(state, batch)
        jax.block_until_ready(m["loss"])

    xplane = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    print("xplane:", xplane)
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data(xplane, "hlo_stats", {})
    obj = json.loads(data) if isinstance(data, (str, bytes)) else data
    with open("/tmp/llama_hlo_stats.json", "w") as f:
        json.dump(obj, f)
    print("wrote /tmp/llama_hlo_stats.json")


if __name__ == "__main__":
    main()
