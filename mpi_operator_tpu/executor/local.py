"""LocalExecutor: a process-level kubelet for TPUJob worker pods.

Watches the ObjectStore for Pods, launches each pod's container command as an
OS process with the pod's env (the controller-injected TPUJOB_* rendezvous
contract included), and mirrors the process lifecycle back into pod status:

  PENDING → (spawn) → RUNNING → SUCCEEDED | FAILED(exit code)

which is exactly the signal the controller's status mirror consumes
(≙ kubelet feeding updateMPIJobStatus,
/root/reference/v2/pkg/controller/mpi_job_controller.go:921-996).

Local DNS shim: pod hostnames like ``<job>-worker-0.<job>-worker`` only
resolve inside a cluster's headless service; locally every "host" shares the
loopback interface, so the coordinator address env is rewritten to
127.0.0.1 (ports disambiguate jobs). This mirrors what the reference's
Intel entrypoint does when it pre-resolves worker hostnames
(examples/pi/intel-entrypoint.sh:27-33) — resolution is an executor concern,
not a workload concern.
"""

from __future__ import annotations

import logging
import os
import queue
import subprocess
import tempfile
import threading
import uuid
from typing import Dict, Optional

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.objects import (
    NODE_NAMESPACE,
    Pod,
    PodPhase,
    bounded_train_stats,
    patch_pod_status,
)
from mpi_operator_tpu.machinery.store import (
    ADDED,
    DELETED,
    MODIFIED,
    NotFound,
    ObjectStore,
)
from mpi_operator_tpu.runtime.emulation import pin_host_device_count
from mpi_operator_tpu.runtime.compile_cache import (
    ENV_CACHE_DIR,
    ENV_CACHE_ENABLED,
)
from mpi_operator_tpu.runtime.stepstats import ENV_STATS_FILE, read_stats

log = logging.getLogger("tpujob.executor")


# dlopen + symbol resolution happen HERE, at import time in the parent:
# the pre-exec hook below runs in the forked child of a heavily threaded
# process, where glibc's allocator/loader locks may be held by a thread
# that no longer exists — an import or CDLL there can deadlock the child
# between fork and exec and the pod never starts. Linux-only; None elsewhere.
try:
    import ctypes as _ctypes
    import signal as _signal

    _LIBC = _ctypes.CDLL("libc.so.6", use_errno=True)
    _LIBC.prctl  # resolve the symbol now, not after fork
    _SIGKILL = int(_signal.SIGKILL)
# oplint: disable=EXC001 — non-Linux / no-glibc platform probe: _LIBC=None
# IS the handled outcome (the hook degrades to a no-op), nothing to log
except Exception:
    _LIBC = None
    _SIGKILL = 9


def _die_with_parent() -> None:
    """Child-side pre-exec hook: SIGKILL this process when the executor
    dies (PR_SET_PDEATHSIG). An executor crash therefore behaves like a
    node crash — no orphan workers silently holding ports/collectives —
    which is exactly what the NodeAgent's restart reconciliation and the
    NodeMonitor's eviction already assume. Only async-signal-safe-ish work
    allowed here (see _LIBC above)."""
    if _LIBC is None:
        return
    try:
        _LIBC.prctl(1, _SIGKILL)  # PR_SET_PDEATHSIG = 1
    # oplint: disable=EXC001 — post-fork pre-exec hook: logging here can
    # deadlock on the logging module's lock held by a vanished thread;
    # only async-signal-safe-ish work is allowed (see _LIBC above)
    except Exception:
        pass

ENV_COORDINATOR = "TPUJOB_COORDINATOR_ADDRESS"
ENV_CONFIG_DIR = "TPUJOB_CONFIG_DIR"
LABEL_JOB_NAME = "tpujob.dev/job-name"
# restart generation the pod was launched for (duplicated from
# controller/controller.py, same as LABEL_JOB_NAME: the executor must not
# import the controller) — launch spans carry it so `ctl trace` can tell
# the checkpoint-resume relaunch from the original generation
LABEL_GENERATION = "tpujob.dev/generation"


class LocalExecutor:
    """Runs every Pod in the store as a local OS process."""

    def __init__(
        self,
        store: ObjectStore,
        *,
        loopback_rewrite: bool = True,
        extra_env: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        require_binding: bool = False,
        logs_dir: Optional[str] = None,
        node_name: Optional[str] = None,
        log_url_base: Optional[str] = None,
        status_sink=None,
        eviction_grace: float = 5.0,
        stepstats_poll: float = 1.0,
        compile_cache_dir: Optional[str] = None,
    ):
        self.store = store
        self.loopback_rewrite = loopback_rewrite
        # kubelet semantics: with a scheduler in play, only bound pods run
        # (spec.node_name set by scheduler/gang.py's atomic admission)
        self.require_binding = require_binding
        # node identity (executor/agent.py): claim ONLY pods bound to this
        # node — the per-node kubelet role; None = run every bound pod
        # (single-node LocalExecutor behavior)
        self.node_name = node_name
        # when set, pod.status.log_path gets f"{base}/<file>" instead of a
        # local filesystem path, so `ctl logs` works cross-node through the
        # agent's log endpoint
        self.log_url_base = log_url_base.rstrip("/") if log_url_base else None
        self.extra_env = dict(extra_env or {})
        self.workdir = workdir
        # when set (agent mode), status mirrors are enqueued here instead of
        # written directly: the NodeAgent flushes the sink together with its
        # Node heartbeat as ONE patch-batch request per tick
        self.status_sink = status_sink
        # eviction termination grace (≙ terminationGracePeriodSeconds): an
        # evicted pod gets SIGTERM first so checkpoint-capable workloads
        # force-save before the SIGKILL lands (ops/elastic.py routes the
        # signal into a gang-synchronized checkpoint-and-exit). 0 = the old
        # immediate-SIGKILL behavior.
        self.eviction_grace = eviction_grace
        self._procs: Dict[str, subprocess.Popen] = {}  # pod key → process
        # pod key → SIGKILL backstop timer of an in-progress graceful
        # termination: a deletion landing inside the grace window (the
        # controller's gang restart deletes evicted pods moments after the
        # monitor/scheduler marked them) must NOT hard-kill the draining
        # process — that would snatch the force-checkpoint window the
        # SIGTERM just granted (kube honors the grace period on delete too)
        self._terminating: Dict[str, threading.Timer] = {}
        # pod key → deleted-but-still-draining predecessor process: a
        # recreated same-name pod (the next restart generation) must not
        # launch until this process exits — the job's coordinator port is
        # stable across generations, so two live generations would collide
        # on the bind (EADDRINUSE → non-retryable crash → burnt backoff)
        self._draining: Dict[str, subprocess.Popen] = {}
        # pod key → (uid, rv) of our last committed status write: anchors
        # the next patch's rv precondition so the mirror stays 1 request
        # (only this executor writes a bound pod's status in steady state).
        # Own lock: _set_phase runs both inside and outside _lock.
        self._status_rv: Dict[str, tuple] = {}
        self._rv_lock = threading.Lock()
        # workload telemetry (ISSUE 15): each launched pod gets a
        # $TPUJOB_STEPSTATS_FILE pointing into the log dir; a poll thread
        # mirrors the worker's flushed blob into pod.status.train_stats —
        # the kubelet-reads-cAdvisor shape, so workers never need store
        # credentials. pod key → {path, ns, name, uid, mtime}
        self.stepstats_poll = stepstats_poll
        self._stats_files: Dict[str, Dict] = {}
        self.logs: Dict[str, tuple] = {}  # pod key → (stdout, stderr)
        # kubelet log dir: pod stdout/stderr stream to files here while the
        # pod runs; the stdout path is stamped into pod.status.log_path so
        # `ctl logs` (any process on this node) can read it
        self.logs_dir = logs_dir or tempfile.mkdtemp(prefix="tpujob-logs-")
        self._config_root = tempfile.mkdtemp(prefix="tpujob-config-")
        # the persistent-compile-cache root (ISSUE 16): NODE-LOCAL and
        # STABLE across pod incarnations — unlike the per-incarnation
        # stepstats/log paths, reuse across restarts is the whole point.
        # Injected as $TPUJOB_COMPILE_CACHE_DIR unless the controller's
        # $TPUJOB_COMPILE_CACHE projection opted the job out; workers
        # namespace their entries by jax version + backend under it
        # (runtime/compile_cache.py), so one dir serves every job safely.
        self.compile_cache_dir = compile_cache_dir or os.path.join(
            self.logs_dir, "compile-cache"
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        self._watch_q = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._watch_q = self.store.watch(None)
        t = threading.Thread(target=self._run, name="local-executor", daemon=True)
        t.start()
        self._threads.append(t)
        if self.stepstats_poll > 0:
            ts = threading.Thread(
                target=self._stats_loop, name="stepstats-poll", daemon=True
            )
            ts.start()
            self._threads.append(ts)
        # adopt objects that existed before the watch began (configs first:
        # pods read the projected dir at launch)
        for cm in self.store.list("ConfigMap"):
            self._project_config(cm)
        for pod in self.store.list("Pod"):
            self._maybe_launch(pod)

    def stop(self) -> None:
        self._stop.set()
        if self._watch_q is not None:
            self.store.stop_watch(self._watch_q)
        with self._lock:
            # draining predecessors included: their grace ends with the
            # executor (same as every other managed process)
            for p in (*self._procs.values(), *self._draining.values()):
                if p.poll() is None:
                    p.kill()

    def join_reapers(self, timeout: float = 2.0) -> None:
        """Wait for in-flight reap threads to finish recording their pods'
        exits (stop() just killed the processes, so they return promptly).
        A stopping NodeAgent calls this before its final batcher flush —
        otherwise the terminal mirrors the reapers are about to enqueue
        would land in a sink nobody drains again."""
        import time

        deadline = time.time() + timeout
        for t in list(self._threads):
            if t.is_alive() and t.name.startswith("reap-"):
                t.join(timeout=max(0.0, deadline - time.time()))

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until no managed process is still running (for tests/CLI)."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if all(p.poll() is not None for p in self._procs.values()):
                    return True
            time.sleep(0.05)
        return False

    # -- internals ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                ev = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                # the delivering event's origin span (the binding patch,
                # the eviction write) parents the launch/evict spans below
                trace.set_delivery(getattr(ev, "trace", None))
                try:
                    if ev.kind == "ConfigMap" and ev.type in (ADDED, MODIFIED):
                        self._project_config(ev.obj)
                    elif ev.kind == "Pod" and ev.type in (ADDED, MODIFIED):
                        self._kill_if_evicted(ev.obj)
                        self._maybe_launch(ev.obj)
                    elif ev.kind == "Pod" and ev.type == DELETED:
                        self._forget(ev.obj)
                finally:
                    trace.clear_delivery()
            except Exception:
                # this thread is the PDEATHSIG parent of every pod process:
                # if it dies, the kernel SIGKILLs all of them. A bad event
                # must never take down the node's workload.
                log.exception("executor event handling failed; continuing")

    def _stats_loop(self) -> None:
        """Mirror each live pod's flushed step-stats blob into
        pod.status.train_stats (the workload telemetry plane, ISSUE 15).
        mtime-gated: an idle worker (or one with stepstats off) costs one
        stat() per poll, zero store writes."""
        while not self._stop.wait(self.stepstats_poll):
            with self._lock:
                entries = list(self._stats_files.items())
            for key, ent in entries:
                try:
                    mtime = os.stat(ent["path"]).st_mtime
                except OSError:
                    continue  # worker never flushed (stepstats dormant)
                if mtime <= ent["mtime"]:
                    continue
                raw = read_stats(ent["path"])
                if raw is None:
                    continue  # torn/unreadable: next poll retries
                ent["mtime"] = mtime
                try:
                    # re-bound at the mirror edge (oplint OBS004), INSIDE
                    # the guard: the file is written by an untrusted
                    # workload — a wrong-typed field must cost one skipped
                    # mirror, never this thread (which serves every pod
                    # on the node)
                    changes = {"train_stats": bounded_train_stats(**raw)}
                    self._mirror_train_stats(ent, changes)
                except Exception:
                    log.warning("train_stats mirror of %s failed", key,
                                exc_info=True)

    def _mirror_train_stats(self, ent: Dict, changes: Dict) -> None:
        if self.status_sink is not None:
            # agent mode: coalesced into the next tick's patch-batch
            # beside the phase mirrors and the heartbeat
            self.status_sink.enqueue(
                ent["ns"], ent["name"], ent["uid"], 0, changes,
            )
            return
        patch_pod_status(
            self.store, ent["ns"], ent["name"], ent["uid"],
            changes, what="stepstats-mirror",
        )

    def _pod_key(self, pod: Pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def _config_dir(self, namespace: str, job_name: str) -> str:
        return os.path.join(self._config_root, namespace, job_name)

    def _project_config(self, cm) -> None:
        """Project a job ConfigMap to files (≙ the kubelet's configMap volume
        sync that elastic Horovod leans on — proposals/elastic-horovod.md:29
        accepts ~1min lag; here it's immediate). Workers read
        $TPUJOB_CONFIG_DIR/hostfile etc. (ops/elastic.declared_world_size)."""
        job_name = cm.metadata.labels.get(LABEL_JOB_NAME, "")
        if not job_name:
            return
        d = self._config_dir(cm.metadata.namespace, job_name)
        os.makedirs(d, exist_ok=True)
        for fname, content in cm.data.items():
            # unique tmp per writer: start()'s adoption pass and the watch
            # thread can project the same ConfigMap concurrently — a shared
            # tmp name let one writer replace the file out from under the
            # other (FileNotFoundError on the loser's os.replace)
            tmp = os.path.join(d, f".{fname}.{uuid.uuid4().hex[:8]}.tmp")
            with open(tmp, "w") as f:
                f.write(content)
            os.replace(tmp, os.path.join(d, fname))  # atomic swap, no torn reads

    def _kill_if_evicted(self, pod: Pod) -> None:
        """Eviction means KILL, not just a status mark (kubelet semantics):
        `ctl drain` / the NodeMonitor force a pod to Failed while its
        process may still be alive here — left running it would keep the
        gang's collectives healthy and the drain would never converge. The
        reaper still runs but terminal status is write-once (_set_phase),
        so the Evicted marker — the retryable signal — survives the
        SIGKILL's rc=-9."""
        if not pod.is_finished():
            return
        key = self._pod_key(pod)
        with self._lock:
            proc = self._procs.get(key)
            already_terminating = key in self._terminating
        if already_terminating:
            # the grace sequence already ran (re-delivered event / relist
            # replay): _kill_externally_finished would return immediately
            # — don't mint a duplicate evict span for it (same noise rule
            # as the launch path's _procs pre-check); the locked re-check
            # inside still guards the real race
            return
        if proc is not None and proc.poll() is None:
            # the kill/grace sequence below is job-scoped work caused by
            # the eviction write delivering right now: span it so `ctl
            # trace` shows WHERE the eviction landed on the node
            with trace.start_span(
                "executor.evict",
                parent=trace.get_delivery(),
                trace_id=pod.metadata.annotations.get(
                    trace.ANNOTATION_TRACE_ID
                ),
                attrs={"pod": key,
                       "reason": pod.status.reason or pod.status.phase,
                       "grace": self.eviction_grace},
            ):
                self._kill_externally_finished(pod, key, proc)

    def _kill_externally_finished(self, pod: Pod, key: str, proc) -> None:
        with self._lock:
            if key in self._terminating:
                # the grace sequence already ran for this process; a
                # re-delivered event (watch-gap relists replay every
                # live object as MODIFIED) must not SIGTERM it again —
                # workloads may treat a second SIGTERM as abort-now,
                # forfeiting the force-checkpoint the grace granted —
                # nor leak the armed backstop timer by overwriting it
                return
        if self.eviction_grace > 0:
            # SIGTERM-then-SIGKILL (≙ the kubelet's graceful pod
            # termination): a preempted checkpointing trainer uses the
            # grace window to force-save at a gang-uniform step, so the
            # relaunched gang resumes instead of replaying from the
            # last periodic save. The backstop timer makes the grace a
            # bound, not a trust: a wedged process still dies.
            log.info(
                "pod %s externally finished (%s); SIGTERM with %.1fs "
                "grace", key, pod.status.reason or pod.status.phase,
                self.eviction_grace,
            )
            proc.terminate()
            timer = threading.Timer(
                self.eviction_grace,
                lambda: proc.poll() is None and proc.kill(),
            )
            timer.daemon = True
            with self._lock:
                self._terminating[key] = timer
            timer.start()
        else:
            log.info("pod %s externally finished (%s); killing its "
                     "process", key, pod.status.reason or pod.status.phase)
            proc.kill()

    def _forget(self, pod: Pod) -> None:
        """Pod deleted (controller restart path / cleanup policy): kill any
        live process and drop all per-pod state, so a recreated pod with the
        same name launches fresh and long-lived executors don't leak."""
        key = self._pod_key(pod)
        with self._lock:
            proc = self._procs.pop(key, None)
            self.logs.pop(key, None)
            draining = self._terminating.pop(key, None)
            self._stats_files.pop(key, None)
        with self._rv_lock:
            self._status_rv.pop(key, None)
        if proc is not None and proc.poll() is None:
            if draining is not None:
                # eviction already granted this process a termination grace
                # (SIGTERM sent, SIGKILL backstop armed): the deletion must
                # not revoke the force-checkpoint window — the armed timer
                # still bounds the process's lifetime, and _maybe_launch
                # holds the key's next incarnation until the reaper
                # confirms this process exited
                with self._lock:
                    self._draining[key] = proc
                return
            proc.kill()

    def _maybe_launch(self, pod: Pod) -> None:
        if pod.status.phase != PodPhase.PENDING:
            return
        if self.require_binding and not pod.spec.node_name:
            return  # waiting for gang admission; binding event re-triggers
        if self.node_name is not None and pod.spec.node_name != self.node_name:
            return  # bound to another node — its agent claims it
        key = self._pod_key(pod)
        if key in self._procs:
            # racy pre-check (re-checked under the lock in _launch): a
            # duplicate delivery / relist replay of a running pod must not
            # mint a noise span
            return
        # the launch span lives in the job's trace (the pod annotation),
        # parented on the event that triggered it — the scheduler's
        # binding patch on generation 0, the recreation after a gang
        # restart on later ones (the checkpoint-resume relaunch `ctl
        # trace` must attribute)
        with trace.start_span(
            "executor.launch",
            parent=trace.get_delivery(),
            trace_id=pod.metadata.annotations.get(trace.ANNOTATION_TRACE_ID),
            attrs={
                "pod": key,
                "node": pod.spec.node_name or "local",
                "generation": pod.metadata.labels.get(LABEL_GENERATION, ""),
            },
        ):
            self._launch(pod, key)

    def _launch(self, pod: Pod, key: str) -> None:
        with self._lock:
            if key in self._procs:
                return
            predecessor = self._draining.get(key)
            if predecessor is not None:
                if predecessor.poll() is None:
                    # the previous generation's process is still inside its
                    # eviction grace: launching now would collide on the
                    # job's stable coordinator port. The predecessor's
                    # reaper re-invokes _maybe_launch once it exits.
                    return
                self._draining.pop(key, None)
            container = pod.spec.container
            argv = list(container.command) + list(container.args)
            if not argv:
                self._set_phase(pod, PodPhase.FAILED, reason="NoCommand")
                return
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update(container.env)
            if self.loopback_rewrite and ENV_COORDINATOR in env:
                addr = env[ENV_COORDINATOR]
                _, _, port = addr.rpartition(":")
                env[ENV_COORDINATOR] = (
                    f"{self._resolve_coordinator_host(pod, addr)}:{port}"
                )
            # The executor owns the device inventory (≙ kubelet device
            # plugin): for cpu-family pods, pin the emulated chip count to
            # the pod's declared chips_per_host, overriding any inherited
            # XLA_FLAGS (e.g. a test harness's 8-device mesh).
            job_name = pod.metadata.labels.get(LABEL_JOB_NAME, "")
            if job_name:
                env[ENV_CONFIG_DIR] = self._config_dir(
                    pod.metadata.namespace, job_name
                )
            if env.get("TPUJOB_ACCELERATOR", "") == "cpu":
                try:
                    chips = max(1, int(env.get("TPUJOB_CHIPS_PER_HOST", "1") or "1"))
                except ValueError:
                    chips = 1  # malformed env must not kill the watch loop
                env["XLA_FLAGS"] = pin_host_device_count(
                    env.get("XLA_FLAGS", ""), chips
                )
            # stream to files (kubelet log dir) instead of pipes: logs
            # survive the executor process and are readable mid-run by
            # `ctl logs`; stdout and stderr stay separate so callers can
            # parse structured stdout (e.g. the bench JSON line) unmixed.
            # The path is unique per incarnation: a restarted same-name pod
            # must not truncate the file an old reaper is about to read
            # (pod.status.log_path always names the current incarnation)
            os.makedirs(self.logs_dir, exist_ok=True)
            base = os.path.join(
                self.logs_dir,
                f"{pod.metadata.namespace}-{pod.metadata.name}"
                f"-{uuid.uuid4().hex[:8]}",
            )
            log_path = base + ".log"
            # the stepstats contract: the worker flushes its bounded blob
            # here (runtime/stepstats.py) and _stats_loop mirrors it into
            # pod.status.train_stats — path is per-incarnation like the
            # log files, so a restarted pod never inherits stale stats
            stats_path = base + ".stats.json"
            env[ENV_STATS_FILE] = stats_path
            # the compile-cache contract (ISSUE 16): a STABLE node-local
            # dir (vs the per-incarnation paths above — restarts reusing
            # it is the feature), gated on the controller's projection of
            # spec.compile_cache; the worker's bootstrap points jax at a
            # version/backend-namespaced subdir
            if env.get(ENV_CACHE_ENABLED, "1") != "0":
                env[ENV_CACHE_DIR] = self.compile_cache_dir
            handles = []
            try:
                f_out = open(log_path, "w")
                handles.append(f_out)
                f_err = open(base + ".err", "w")
                handles.append(f_err)
                proc = subprocess.Popen(
                    argv,
                    env=env,
                    cwd=self.workdir,
                    stdout=f_out,
                    stderr=f_err,
                    text=True,
                    preexec_fn=_die_with_parent,
                )
            except OSError as e:
                log.warning("pod %s failed to start: %s", key, e)
                self._set_phase(pod, PodPhase.FAILED, reason=f"StartError: {e}")
                return
            finally:
                # the child holds the fds now (or the spawn failed): either
                # way these handles are done
                for f in handles:
                    f.close()
            self._procs[key] = proc
            self._stats_files[key] = {
                "path": stats_path, "ns": pod.metadata.namespace,
                "name": pod.metadata.name, "uid": pod.metadata.uid,
                "mtime": 0.0,
            }
        stamped = log_path
        if self.log_url_base:
            stamped = f"{self.log_url_base}/{os.path.basename(log_path)}"
        self._set_phase(pod, PodPhase.RUNNING, ip="127.0.0.1", log_path=stamped)
        t = threading.Thread(
            target=self._reap, args=(pod, proc, base), name=f"reap-{key}",
            daemon=True,
        )
        t.start()
        # prune finished reap threads so per-pod state doesn't accumulate
        self._threads = [th for th in self._threads if th.is_alive()]
        self._threads.append(t)

    def _resolve_coordinator_host(self, pod: Pod, addr: str) -> str:
        """The DNS role: ``<job>-worker-0.<subdomain>`` only resolves inside
        a cluster's headless service. Single-node executors rewrite to
        loopback (ports disambiguate jobs). A node agent resolves through
        the store instead: coordinator pod → its bound node → that node's
        advertised address (binding precedes launch under gang admission,
        so the lookup is race-free)."""
        if self.node_name is None:
            return "127.0.0.1"
        host, _, _ = addr.rpartition(":")
        coord_pod_name = host.split(".", 1)[0]
        coord = self.store.try_get(
            "Pod", pod.metadata.namespace, coord_pod_name
        )
        if coord is not None and coord.spec.node_name:
            node = self.store.try_get("Node", NODE_NAMESPACE, coord.spec.node_name)
            if node is not None and node.status.address:
                return node.status.address
        return "127.0.0.1"

    def _reap(self, pod: Pod, proc: subprocess.Popen, base: str) -> None:
        proc.wait()
        key = self._pod_key(pod)
        with self._lock:
            timer = self._terminating.pop(key, None)
            was_draining = self._draining.get(key) is proc
            if was_draining:
                self._draining.pop(key)
        if timer is not None:
            timer.cancel()  # exited inside its grace: no backstop needed
        out = err = ""
        try:
            with open(base + ".log") as f:
                out = f.read()
            with open(base + ".err") as f:
                err = f.read()
        except OSError:
            pass  # log files are best-effort; phase/exit code still land
        self.logs[self._pod_key(pod)] = (out, err)
        try:
            if proc.returncode == 0:
                self._set_phase(pod, PodPhase.SUCCEEDED, exit_code=0)
            else:
                tail = (err or out or "").strip()[-1024:]  # ≙ truncateMessage(:1524)
                self._set_phase(
                    pod, PodPhase.FAILED, reason=f"ExitCode{proc.returncode}",
                    message=tail, exit_code=proc.returncode,
                )
        except Exception:
            # store gone mid-teardown (closed sqlite, hard outage past the
            # client's retry window): the mirror is lost but the thread
            # must not die noisily — the monitor's eviction is the backstop
            log.warning("pod %s exit mirror failed", self._pod_key(pod),
                        exc_info=True)
        log.info(
            "pod %s exited rc=%d", self._pod_key(pod), proc.returncode
        )
        if was_draining:
            # the next generation may already be bound and waiting on this
            # exit (its binding event fired while we were draining, and
            # _maybe_launch deferred it): level-trigger the launch now
            try:
                cur = self.store.try_get(
                    "Pod", pod.metadata.namespace, pod.metadata.name
                )
                if cur is not None:
                    self._maybe_launch(cur)
            except Exception:
                log.warning("post-drain relaunch check for %s failed", key,
                            exc_info=True)

    def _set_phase(
        self,
        pod: Pod,
        phase: str,
        *,
        reason: str = "",
        ip: str = "",
        message: str = "",
        exit_code: Optional[int] = None,
        log_path: str = "",
    ) -> None:
        # status mirror over the PATCH verb (status subresource — the only
        # write scope the NODE token tier needs): one request in the
        # common case, with the same guards the old GET+PUT loop enforced —
        # incarnation (uid) and write-once terminal — carried by
        # patch_pod_status's rv precondition + conflict re-check. The
        # snapshot anchoring the rv is the watch event that triggered the
        # launch (binding is its freshest write) or our own last committed
        # status, so the precondition almost never misses.
        changes = {
            "phase": phase,
            "ready": phase == PodPhase.RUNNING,
            "reason": reason,
        }
        if message:
            changes["message"] = message
        if ip:
            changes["pod_ip"] = ip
        if exit_code is not None:
            changes["exit_code"] = exit_code
        if log_path:
            changes["log_path"] = log_path
        key = self._pod_key(pod)
        if self.status_sink is not None:
            # agent mode: the sink coalesces this with every other dirty
            # mirror and the Node heartbeat into ONE patch-batch request
            # per tick (O(pods) requests → O(1)); ordering per pod is
            # preserved, commit is asynchronous but prompt (the sink wakes
            # its flusher). The sink owns the rv anchoring there
            # (StatusBatcher._committed) — _status_rv is the DIRECT path's
            # anchor only.
            self.status_sink.enqueue(
                pod.metadata.namespace, pod.metadata.name, pod.metadata.uid,
                pod.metadata.resource_version or 0, changes,
            )
            return
        with self._rv_lock:
            known = self._status_rv.get(key)
        expected_rv = pod.metadata.resource_version or 0
        if known is not None and known[0] == pod.metadata.uid:
            expected_rv = max(expected_rv, known[1])
        from mpi_operator_tpu.machinery.objects import patch_pod_status

        committed = patch_pod_status(
            self.store, pod.metadata.namespace, pod.metadata.name,
            pod.metadata.uid, changes, expected_rv=expected_rv,
            what="set-phase",
        )
        if committed is not None:
            with self._rv_lock:
                self._status_rv[key] = (
                    committed.metadata.uid,
                    committed.metadata.resource_version,
                )
