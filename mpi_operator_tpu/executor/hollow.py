"""Hollow node agents: kubemark for the TPU control plane.

≙ kubernetes' kubemark/hollow-node: to measure the control plane at 1k
nodes / 10k jobs you do not need 1k machines — you need 1k agents that
exercise every CONTROL-PLANE path for real (watch, bind pickup, status
patch-batches, Node heartbeats) while faking only the one thing that
needs hardware: running the process. This module supplies that fake:

- :class:`HollowExecutor` duck-types the LocalExecutor surface the
  NodeAgent drives (start/stop/join_reapers/wait_idle/status_sink), but
  instead of ``subprocess.Popen`` it walks each claimed pod through a
  SCRIPTED phase timeline — Pending → Running after ``pending_s`` →
  Succeeded/Failed after ``run_s`` (seeded per-pod jitter + failure
  rate) — mirroring every transition through the SAME StatusBatcher /
  ``patch_pod_status`` machinery a real agent uses, so the store sees
  byte-identical traffic shapes and the chaos invariants
  (tests/invariants.py) hold over hollow trails too.
- ``NodeAgent(..., hollow=HollowTimeline(...))`` (the ``--hollow`` agent
  flag) runs the REAL agent loop — registration, heartbeat ticks, batch
  flushes, eviction handling — over a hollow executor: one process, one
  node, zero workload processes.
- :class:`HollowFleet` packs N hollow nodes into ONE process for the
  scale bench: a single shared watch (fan-in, not N long-polls), a
  single timer wheel (not N threads), heartbeats staggered across the
  interval and shipped in CHUNKED patch-batches together with the dirty
  pod mirrors — one host simulates 1k nodes / 100k pods against a real
  StoreServer (``BENCH_CP_MODES=scale``).

Run a fleet standalone against a live store::

  python -m mpi_operator_tpu.executor.hollow \\
      --store http://127.0.0.1:8475 --nodes 1000 --chips 32
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    NODE_NAMESPACE,
    TRAIN_BUCKETS,
    Node,
    Pod,
    PodPhase,
    bounded_serve_stats,
    bounded_train_stats,
    patch_pod_status,
)
from mpi_operator_tpu.machinery.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    NotFound,
)

log = logging.getLogger("tpujob.hollow")


@dataclass
class HollowTimeline:
    """The scripted pod lifecycle (≙ kubemark's pod lifecycle knobs).

    ``pending_s``: bind-pickup → Running delay (scheduler-visible launch
    latency). ``run_s`` + uniform ``run_jitter_s``: Running → terminal.
    ``failure_rate``: probability the terminal phase is Failed with
    ``failure_exit_code`` (drawn from a PER-POD rng seeded by ``seed`` +
    the pod's identity, so a rerun of the same fleet is deterministic).

    Serving pods (label ``tpujob.dev/job-role: serve``) follow a SECOND
    timeline: Pending → Running (ready=False) → ready after
    ``serve_warmup_s`` (the readiness gate — scripted model load) → stay
    Running forever, mirroring synthetic ``status.serve_stats`` samples
    every ``serve_stats_interval_s`` drawn from ``load`` (a shared
    :class:`ServeLoadModel`). No terminal transition: long-lived is the
    point.
    """

    pending_s: float = 0.0
    run_s: float = 0.2
    run_jitter_s: float = 0.0
    failure_rate: float = 0.0
    failure_exit_code: int = 1
    seed: int = 0
    serve_warmup_s: float = 0.2
    serve_stats_interval_s: float = 0.5
    load: Optional["ServeLoadModel"] = None
    # training telemetry (the workload telemetry plane, ISSUE 15): when a
    # TrainLoadModel is attached, every batch worker pod mirrors synthetic
    # ``status.train_stats`` blobs (stall-attributed bucket seconds + step
    # counters) every ``train_stats_interval_s`` — the hollow twin of the
    # real step loop's stepstats file, so goodput/straggler aggregation
    # benches at fleet scale with zero training processes
    train: Optional["TrainLoadModel"] = None
    train_stats_interval_s: float = 0.5
    # checkpoint-resume (the soak bench, ISSUE 18): when set, a batch
    # pod's scripted runtime is a stable per-POD total (seeded by pod
    # identity, not incarnation uid) and progress accrues across
    # incarnations — a checkpoint-then-migrated gang finishes the
    # REMAINDER of its work instead of starting over, which is the
    # operator's whole migration contract. Off by default: restart tests
    # rely on each incarnation re-running the full clock.
    checkpoint_resume: bool = False
    _ckpt_done: Dict[str, float] = field(default_factory=dict, repr=False)
    _ckpt_run_start: Dict[str, float] = field(default_factory=dict,
                                              repr=False)
    _ckpt_lock: threading.Lock = field(default_factory=threading.Lock,
                                       repr=False)

    def pod_rng(self, namespace: str, name: str, uid: str) -> random.Random:
        return random.Random(f"{self.seed}:{namespace}/{name}:{uid}")

    # -- checkpoint-resume bookkeeping (fleet-shared: a migrated pod
    # lands on a DIFFERENT node's executor, so progress lives here) -----

    def ckpt_remaining(self, key: str, total: float) -> float:
        with self._ckpt_lock:
            return max(0.05, total - self._ckpt_done.get(key, 0.0))

    def ckpt_mark_running(self, key: str) -> None:
        with self._ckpt_lock:
            self._ckpt_run_start.setdefault(key, time.monotonic())

    def ckpt_pause(self, key: str) -> None:
        """Pod torn down mid-run (eviction): bank the progress."""
        with self._ckpt_lock:
            t0 = self._ckpt_run_start.pop(key, None)
            if t0 is not None:
                self._ckpt_done[key] = (self._ckpt_done.get(key, 0.0)
                                        + (time.monotonic() - t0))

    def ckpt_finish(self, key: str) -> None:
        with self._ckpt_lock:
            self._ckpt_run_start.pop(key, None)
            self._ckpt_done.pop(key, None)


# serving-pod identity labels (duplicated string constants — the executor
# deliberately does not import the controller packages, same posture as
# the agent; controller/serve.py's tests pin the values stay identical)
LABEL_ROLE = "tpujob.dev/job-role"
LABEL_SERVE_NAME = "tpujob.dev/serve-name"
LABEL_JOB_NAME = "tpujob.dev/job-name"
ROLE_SERVE = "serve"


class ServeLoadModel:
    """Synthetic closed-loop serving load for hollow fleets.

    The bench's traffic generator declares OFFERED aggregate QPS per serve
    (``set_offered``); running hollow serving pods register themselves and
    draw their share (offered / registered pods) plus derived queue depth
    and p99 from an M/M/1-shaped utilization curve against
    ``capacity_qps`` per pod. The loop this closes is the real one the
    autoscaler lives in: more replicas → lower per-pod utilization →
    lower latency/queue → scale-down pressure, and vice versa — so a
    BENCH_CP_MODES=serve run exercises the actual feedback dynamics, not
    a canned metrics tape.
    """

    def __init__(self, *, capacity_qps: float = 100.0,
                 base_ms: float = 20.0):
        self.capacity_qps = capacity_qps
        self.base_ms = base_ms
        self._lock = threading.Lock()
        self._offered: Dict[str, float] = {}      # serve key → total QPS
        self._pods: Dict[str, set] = {}           # serve key → pod keys

    def set_offered(self, serve_key: str, qps: float) -> None:
        with self._lock:
            self._offered[serve_key] = max(0.0, qps)

    def offered(self, serve_key: str) -> float:
        with self._lock:
            return self._offered.get(serve_key, 0.0)

    def register(self, serve_key: str, pod_key: str) -> None:
        with self._lock:
            self._pods.setdefault(serve_key, set()).add(pod_key)

    def unregister(self, serve_key: str, pod_key: str) -> None:
        with self._lock:
            pods = self._pods.get(serve_key)
            if pods is not None:
                pods.discard(pod_key)
                if not pods:
                    del self._pods[serve_key]

    def serving_pods(self, serve_key: str) -> int:
        with self._lock:
            return len(self._pods.get(serve_key, ()))

    def sample(self, serve_key: str) -> Dict[str, float]:
        """One pod's current stats: its share of the offered load and the
        utilization-derived queue/latency (clamped — an overloaded pod
        reports a deep-but-finite queue, like a bounded request queue)."""
        with self._lock:
            offered = self._offered.get(serve_key, 0.0)
            n = len(self._pods.get(serve_key, ()))
        per_pod = offered / n if n else 0.0
        u = per_pod / self.capacity_qps if self.capacity_qps > 0 else 0.0
        if u < 0.95:
            queue = u / (1.0 - u)
        else:
            queue = 19.0 + (u - 0.95) * 200.0  # saturated: queue blows up
        queue = min(queue, 500.0)
        p99 = self.base_ms * (1.0 + 3.0 * u + queue)
        return {
            "qps": round(per_pod, 3),
            "queue_depth": round(queue, 3),
            "p99_ms": round(p99, 3),
        }


class TrainLoadModel:
    """Synthetic per-pod training timelines for hollow fleets — the batch
    twin of :class:`ServeLoadModel` (the workload telemetry plane,
    ISSUE 15).

    Each registered worker pod advances a seeded synthetic step clock on
    every stats tick: wall time splits into the TRAIN_BUCKETS taxonomy by
    a steady-state profile (mostly ``compute``), the first tick charges a
    one-shot ``compile`` phase, and two seeded fault knobs exist so the
    goodput aggregator has something real to attribute:

    - :meth:`set_stall` shifts a fraction of a whole JOB's step wall time
      into one named bucket (e.g. an input-pipeline stall: steps stretch
      and the stolen time accrues to ``input``);
    - :meth:`set_straggler` multiplies ONE pod's step time (a slow host:
      its step p50 diverges from the gang median — the skew signal).

    Cumulative counters are PER POD INCARNATION (keyed by pod uid at
    registration), so a relaunched gang restarts its counters from zero —
    exactly the counter-reset shape the aggregator's deltas must absorb.

    The persistent-compile-cache twin (ISSUE 16): the FIRST incarnation
    of a pod key charges the full ``compile_s`` (cold — jax writes the
    cache); every LATER incarnation of the same pod key charges only
    ``warm_compile_s`` (warm — the relaunch reads the node-local cache),
    and the blob's ``compile_cache`` field reports the matching hit/miss
    counts. Hollow restart benches therefore show the same
    restart_to_first_step_seconds collapse the real cache produces.
    """

    # steady-state wall-time split of a healthy step
    PROFILE = {"compute": 0.86, "input": 0.05, "sync": 0.06, "ckpt": 0.03}

    def __init__(self, *, step_ms: float = 50.0, compile_s: float = 1.0,
                 warm_compile_s: Optional[float] = None, seed: int = 0):
        self.step_ms = step_ms
        self.compile_s = compile_s
        # measured shape on the real CPU twin: a warm restart pays ~1/10
        # of the cold compile (deserialize + link, not recompile)
        self.warm_compile_s = (compile_s / 10.0 if warm_compile_s is None
                               else warm_compile_s)
        self.seed = seed
        # pod keys that have EVER finished a compile — deliberately NOT
        # per-uid: the cache dir outlives incarnations, that's the point
        self._warm: set = set()
        self._lock = threading.Lock()
        # (pod_key, uid) → {"steps": float, "buckets": {...}, "p50": ms}
        self._pods: Dict[tuple, Dict[str, Any]] = {}
        self._stalls: Dict[str, tuple] = {}       # job key → (bucket, frac)
        self._stragglers: Dict[str, float] = {}   # pod key → step factor

    def set_stall(self, job_key: str, bucket: str, fraction: float) -> None:
        if bucket not in TRAIN_BUCKETS:
            raise ValueError(f"unknown stall bucket {bucket!r} "
                             f"(one of {TRAIN_BUCKETS})")
        if not 0.0 < fraction < 1.0:
            raise ValueError("stall fraction must be in (0, 1)")
        with self._lock:
            self._stalls[job_key] = (bucket, fraction)

    def clear_stall(self, job_key: str) -> None:
        with self._lock:
            self._stalls.pop(job_key, None)

    def set_straggler(self, pod_key: str, factor: float) -> None:
        if factor <= 0:
            raise ValueError("straggler factor must be > 0")
        with self._lock:
            self._stragglers[pod_key] = factor

    def clear_straggler(self, pod_key: str) -> None:
        with self._lock:
            self._stragglers.pop(pod_key, None)

    def forget(self, pod_key: str, uid: str) -> None:
        with self._lock:
            self._pods.pop((pod_key, uid), None)

    def advance(self, job_key: str, pod_key: str, uid: str,
                dt: float) -> Dict[str, Any]:
        """Advance one pod's synthetic clock by ``dt`` wall seconds and
        return its bounded train_stats blob. Deterministic per (seed,
        pod identity): two runs of one seeded fleet produce identical
        tapes."""
        with self._lock:
            st = self._pods.get((pod_key, uid))
            if st is None:
                rng = random.Random(f"{self.seed}:{pod_key}:{uid}")
                # the compile-cache twin: a pod key that compiled before
                # restarts WARM (the node-local cache survived the pod)
                warm = pod_key in self._warm
                st = self._pods[(pod_key, uid)] = {
                    "steps": 0.0,
                    "buckets": {k: 0.0 for k in TRAIN_BUCKETS},
                    "jitter": 1.0 + rng.uniform(-0.03, 0.03),
                    "compiled": False,
                    "warm": warm,
                    "compile_s": (self.warm_compile_s if warm
                                  else self.compile_s),
                }
            stall = self._stalls.get(job_key)
            factor = self._stragglers.get(pod_key, 1.0)
        remaining = dt
        if not st["compiled"]:
            # one-shot compile charge at the head of the incarnation
            spent = min(st["compile_s"], remaining)
            st["buckets"]["compile"] += spent
            remaining -= spent
            if st["buckets"]["compile"] >= st["compile_s"] - 1e-9:
                st["compiled"] = True
                with self._lock:
                    self._warm.add(pod_key)
        base_s = self.step_ms / 1e3 * st["jitter"] * factor
        if stall is not None:
            # the stall steals `frac` of every step's wall time: the
            # effective step stretches and the stolen share accrues to
            # the named bucket
            bucket, frac = stall
            step_s = base_s / max(1e-9, 1.0 - frac)
        else:
            bucket, frac = "", 0.0
            step_s = base_s
        if remaining > 0:
            st["steps"] += remaining / step_s
            healthy = remaining * (base_s / step_s)
            for k, share in self.PROFILE.items():
                st["buckets"][k] += healthy * share
            if stall is not None:
                st["buckets"][bucket] += remaining - healthy
        p50 = step_s * 1e3
        return bounded_train_stats(
            step=int(st["steps"]), steps=int(st["steps"]),
            step_p50_ms=p50, buckets=st["buckets"],
            # mirror the real worker's warm-vs-cold signal: one synthetic
            # program, hit on a warm restart, missed on a cold start
            compile_cache={"hits": 1, "misses": 0} if st["warm"]
            else {"hits": 0, "misses": 1},
        )


@dataclass
class MaintenanceSchedule:
    """Seeded rolling-maintenance notices for a hollow fleet (ISSUE 14):
    the rehearsal harness for the disruption plane. ``fraction`` of the
    fleet (chosen by a seeded rng — two runs of one seed pick the same
    victims in the same order) receives a ``tpujob.dev/maintenance-at``
    notice: the first at ``start_s`` after fleet start, one more every
    ``stagger_s`` (the rolling wave), each with ``notice_s`` of warning
    before its deadline. The DrainController takes it from there."""

    fraction: float = 0.2
    notice_s: float = 10.0
    start_s: float = 2.0
    stagger_s: float = 0.5
    seed: int = 0

    def victims(self, node_names: List[str]) -> List[str]:
        k = max(1, round(self.fraction * len(node_names)))
        rng = random.Random(f"maintenance:{self.seed}")
        return rng.sample(sorted(node_names), min(k, len(node_names)))


class _TimerWheel:
    """One thread serving many scheduled callbacks (heapq): 100k hollow
    pods cannot afford a threading.Timer thread each. Handles are dicts
    with a ``cancelled`` flag — cancel is O(1), the heap entry is skipped
    at fire time.

    ``clock`` (anything with ``to_wall(virtual_seconds)``, e.g.
    ``machinery.scenario.VirtualClock``) lets callers schedule in
    SCENARIO time: ``schedule(delay, fn, virtual=True)`` converts the
    delay through the clock, so a compressed soak's maintenance wave
    fires at deterministic scenario offsets instead of wall-clock ones.
    """

    def __init__(self, clock: Any = None):
        self._cond = threading.Condition()
        self._heap: List[tuple] = []
        self._seq = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._clock = clock

    def start(self) -> "_TimerWheel":
        with self._cond:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._run, name="hollow-timer-wheel", daemon=True
                )
                self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    def schedule(self, delay: float, fn, *,
                 virtual: bool = False) -> Dict[str, Any]:
        if virtual and self._clock is not None:
            delay = self._clock.to_wall(delay)
        handle = {"cancelled": False, "fn": fn}
        with self._cond:
            self._seq += 1
            heapq.heappush(
                self._heap, (time.monotonic() + max(0.0, delay),
                             self._seq, handle)
            )
            self._cond.notify()
        return handle

    @staticmethod
    def cancel(handle: Dict[str, Any]) -> None:
        handle["cancelled"] = True
        handle["fn"] = None  # drop the closure (and its pod) promptly

    def pending(self) -> int:
        with self._cond:
            return sum(
                1 for (_, _, h) in self._heap if not h["cancelled"]
            )

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._heap:
                    self._cond.wait(0.5)  # bounded: observes stop
                    continue
                due, _, handle = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._cond.wait(min(due - now, 0.5))
                    continue
                heapq.heappop(self._heap)
                fn = None if handle["cancelled"] else handle["fn"]
            if fn is None:
                continue
            try:
                fn()
            except Exception:
                # one pod's transition must not stall the whole wheel
                log.exception("hollow timer callback failed; continuing")


class HollowExecutor:
    """Scripted phase transitions behind the LocalExecutor surface.

    Claims pods exactly like the real executor (bound to ``node_name``,
    Pending), then walks them through the :class:`HollowTimeline` instead
    of spawning processes. Mirrors ride ``status_sink`` (the NodeAgent's
    StatusBatcher → one patch-batch per tick) when present, direct
    uid+rv-guarded ``patch_pod_status`` otherwise — the same write paths,
    guards included, as the real agent.
    """

    def __init__(self, store, *, node_name: str,
                 timeline: Optional[HollowTimeline] = None,
                 status_sink=None, wheel: Optional[_TimerWheel] = None,
                 external_events: bool = False,
                 logs_dir: str = ""):
        self.store = store
        self.node_name = node_name
        self.timeline = timeline or HollowTimeline()
        self.status_sink = status_sink
        self.logs_dir = logs_dir
        self.log_url_base: Optional[str] = None  # NodeAgent stamps; unused
        # fleet mode: the fleet owns ONE watch and routes events here via
        # handle_event() — N nodes, one long-poll, not N
        self._external_events = external_events
        self._own_wheel = wheel is None
        self._wheel = wheel or _TimerWheel()
        self._lock = threading.Lock()
        # pod key → uid of the incarnation whose timeline is scheduled or
        # finished (relist replays / duplicate deliveries are no-ops)
        self._seen: Dict[str, str] = {}
        # pod key → live wheel handles (cancelled on delete/evict)
        self._handles: Dict[str, List[Dict[str, Any]]] = {}
        # serving pods: pod key → its serve key (for load-model
        # unregistration) and pod key → the CURRENT recurring stats-tick
        # handle (replaced on every re-arm so handle lists stay bounded)
        self._serve_keys: Dict[str, str] = {}
        self._stats_handles: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._watch_q = None

    # -- lifecycle (the NodeAgent-driven surface) ---------------------------

    def start(self) -> None:
        self._wheel.start()
        if not self._external_events:
            self._watch_q = self.store.watch(None)
            t = threading.Thread(
                target=self._run, name=f"hollow-{self.node_name}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
            # adopt pods bound before the watch began (level-triggered,
            # same as LocalExecutor.start's adoption pass)
            for pod in self.store.list("Pod"):
                self.observe(pod)

    def stop(self) -> None:
        self._stop.set()
        if self._watch_q is not None:
            self.store.stop_watch(self._watch_q)
        with self._lock:
            handles = [h for hs in self._handles.values() for h in hs]
            handles += list(self._stats_handles.values())
            self._handles.clear()
            self._stats_handles.clear()
        for h in handles:
            _TimerWheel.cancel(h)
        if self._own_wheel:
            self._wheel.stop()

    def join_reapers(self, timeout: float = 2.0) -> None:
        """No reap threads exist — transitions ride the timer wheel; the
        surface exists so NodeAgent.stop() runs unchanged."""

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until no scheduled transition is outstanding."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not any(
                    not h["cancelled"]
                    for hs in self._handles.values() for h in hs
                ):
                    return True
            time.sleep(0.02)
        return False

    # -- event intake -------------------------------------------------------

    def _run(self) -> None:
        import queue

        while not self._stop.is_set():
            try:
                ev = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.handle_event(ev)
            except Exception:
                log.exception("hollow event handling failed; continuing")

    def handle_event(self, ev) -> None:
        """One watch event (fleet routing entry point)."""
        if ev.kind != "Pod":
            return
        if ev.type == DELETED:
            self._forget(ev.obj)
        elif ev.type in (ADDED, MODIFIED):
            self.observe(ev.obj)

    def observe(self, pod: Pod) -> None:
        """Level-triggered pickup: schedule the timeline for a newly bound
        incarnation; cancel it when the pod finished externally (eviction
        — the kubelet-kill equivalent: the 'process' dies with it)."""
        if pod.spec.node_name != self.node_name:
            return
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        uid = pod.metadata.uid
        if pod.is_finished():
            # external terminal (monitor eviction, drain): kill the
            # scripted timeline exactly like a SIGKILL kills a process;
            # _seen keeps the uid so a relist replay cannot resurrect it
            with self._lock:
                self._seen[key] = uid
                handles = self._handles.pop(key, [])
                stats = self._stats_handles.pop(key, None)
                serve_key = self._serve_keys.pop(key, None)
            for h in handles:
                _TimerWheel.cancel(h)
            if stats is not None:
                _TimerWheel.cancel(stats)
            if serve_key is not None and self.timeline.load is not None:
                self.timeline.load.unregister(serve_key, key)
            if self.timeline.train is not None:
                self.timeline.train.forget(key, uid)
            return
        if pod.status.phase not in (PodPhase.PENDING, PodPhase.RUNNING):
            return
        with self._lock:
            if self._seen.get(key) == uid:
                return  # duplicate delivery / relist replay
            self._seen[key] = uid
            self._handles[key] = []
        # a pod already RUNNING on first sight is a restarted hollow
        # agent/fleet adopting its prior claims (the real agent's analog
        # is _evict_orphans — here the scripted 'process' can simply
        # resume): skip the Running mirror, arm only the terminal
        # transition, or the pod would stay Running forever
        self._schedule_timeline(
            pod, key, uid,
            already_running=pod.status.phase == PodPhase.RUNNING,
        )

    def _forget(self, pod: Pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            self._seen.pop(key, None)
            handles = self._handles.pop(key, [])
            stats = self._stats_handles.pop(key, None)
            serve_key = self._serve_keys.pop(key, None)
        for h in handles:
            _TimerWheel.cancel(h)
        if stats is not None:
            _TimerWheel.cancel(stats)
        if serve_key is not None and self.timeline.load is not None:
            self.timeline.load.unregister(serve_key, key)
        if self.timeline.train is not None:
            self.timeline.train.forget(key, pod.metadata.uid)
        if self.timeline.checkpoint_resume and serve_key is None:
            # torn down mid-run (eviction): bank the progress so the
            # replacement incarnation runs only the remainder (no-op if
            # the pod already reached terminal — ckpt_finish cleared it)
            self.timeline.ckpt_pause(key)

    # -- the scripted lifecycle ---------------------------------------------

    def _schedule_timeline(self, pod: Pod, key: str, uid: str,
                           already_running: bool = False) -> None:
        if pod.metadata.labels.get(LABEL_ROLE) == ROLE_SERVE:
            self._schedule_serve_timeline(pod, key, uid, already_running)
            return
        tl = self.timeline
        rng = tl.pod_rng(pod.metadata.namespace, pod.metadata.name, uid)
        failed = rng.random() < tl.failure_rate
        ns, name = pod.metadata.namespace, pod.metadata.name
        if tl.checkpoint_resume:
            # stable per-POD total seeded by identity (not incarnation
            # uid): every incarnation agrees on how much work the pod
            # holds, and a checkpoint-then-migrated replacement runs
            # only the remainder
            srng = tl.pod_rng(ns, name, "ckpt")
            total = tl.run_s + srng.uniform(0.0, tl.run_jitter_s)
            run_s = tl.ckpt_remaining(key, total)
        else:
            run_s = tl.run_s + rng.uniform(0.0, tl.run_jitter_s)
        rv = pod.metadata.resource_version or 0

        def to_running():
            if tl.checkpoint_resume:
                tl.ckpt_mark_running(key)
            self._mirror(ns, name, uid, rv, {
                "phase": PodPhase.RUNNING, "ready": True, "reason": "",
                "pod_ip": "127.0.0.1",
            })

        def train_tick():
            # synthetic train_stats mirror (workload telemetry, ISSUE 15):
            # rides the same recurring-handle discipline as serve stats —
            # the recurrence dies with the incarnation, never past it
            with self._lock:
                if self._seen.get(key) != uid or self._stop.is_set():
                    return
            tl_ = self.timeline
            job_key = f"{ns}/{pod.metadata.labels.get(LABEL_JOB_NAME, '')}"
            # advance() already emits the bounded shape; re-bounding at
            # the mirror edge keeps the blessed OBS004 form visible here
            stats = bounded_train_stats(**tl_.train.advance(
                job_key, key, uid, tl_.train_stats_interval_s))
            # rv=0: a stats mirror may always apply to the live
            # incarnation (same posture as the serve stats tick)
            self._mirror(ns, name, uid, 0, {"train_stats": stats})
            handle = self._wheel.schedule(tl_.train_stats_interval_s,
                                          train_tick)
            with self._lock:
                if self._seen.get(key) == uid:
                    self._stats_handles[key] = handle
                else:
                    _TimerWheel.cancel(handle)

        def to_terminal():
            with self._lock:
                if self._seen.get(key) != uid:
                    return  # deleted/recreated while the timer was armed
                self._handles.pop(key, None)
            if tl.checkpoint_resume:
                tl.ckpt_finish(key)
            if failed:
                self._mirror(ns, name, uid, rv, {
                    "phase": PodPhase.FAILED, "ready": False,
                    "reason": f"ExitCode{tl.failure_exit_code}",
                    "message": "hollow scripted failure",
                    "exit_code": tl.failure_exit_code,
                })
            else:
                self._mirror(ns, name, uid, rv, {
                    "phase": PodPhase.SUCCEEDED, "ready": False,
                    "reason": "", "exit_code": 0,
                })

        handles = []
        if not already_running:
            handles.append(self._wheel.schedule(tl.pending_s, to_running))
            handles.append(
                self._wheel.schedule(tl.pending_s + run_s, to_terminal)
            )
        else:
            # adopted mid-run: remaining runtime unknowable — restart the
            # scripted clock from now (a restarted real process would
            # also start over; under checkpoint_resume run_s is already
            # the banked remainder, so the clock starts accruing now)
            if tl.checkpoint_resume:
                tl.ckpt_mark_running(key)
            handles.append(self._wheel.schedule(run_s, to_terminal))
        stats_handle = None
        if tl.train is not None and pod.metadata.labels.get(LABEL_JOB_NAME):
            # first synthetic train_stats tick once the pod is "running";
            # the tick re-arms itself (replacing _stats_handles[key], the
            # serve-stats recurrence discipline)
            first_delay = (tl.train_stats_interval_s if already_running
                           else tl.pending_s + tl.train_stats_interval_s)
            stats_handle = self._wheel.schedule(first_delay, train_tick)
        with self._lock:
            if self._seen.get(key) == uid and key in self._handles:
                self._handles[key].extend(handles)
                if stats_handle is not None:
                    self._stats_handles[key] = stats_handle
            else:
                # evicted/deleted between scheduling and recording
                for h in handles:
                    _TimerWheel.cancel(h)
                if stats_handle is not None:
                    _TimerWheel.cancel(stats_handle)

    def _schedule_serve_timeline(self, pod: Pod, key: str, uid: str,
                                 already_running: bool = False) -> None:
        """The long-lived serving lifecycle: Running (not ready) →
        readiness gate after warmup → recurring synthetic serve_stats
        mirrors, forever. Termination only ever comes from OUTSIDE
        (eviction, drain, controller teardown) — handled by observe()'s
        finish branch like any kubelet kill."""
        tl = self.timeline
        ns, name = pod.metadata.namespace, pod.metadata.name
        rv = pod.metadata.resource_version or 0
        serve_key = f"{ns}/{pod.metadata.labels.get(LABEL_SERVE_NAME, '')}"
        with self._lock:
            self._serve_keys[key] = serve_key

        def stats_tick():
            with self._lock:
                if self._seen.get(key) != uid or self._stop.is_set():
                    return  # evicted/replaced: the recurrence dies here
            stats = bounded_serve_stats(
                **(tl.load.sample(serve_key) if tl.load is not None else {})
            )
            # rv=0: no precondition — a stats mirror may always apply to
            # the live incarnation (patch_pod_status still enforces the
            # uid + write-once-terminal guards on the re-read path)
            self._mirror(ns, name, uid, 0, {"serve_stats": stats})
            handle = self._wheel.schedule(tl.serve_stats_interval_s,
                                          stats_tick)
            with self._lock:
                if self._seen.get(key) == uid:
                    self._stats_handles[key] = handle
                else:
                    _TimerWheel.cancel(handle)

        def to_running():
            self._mirror(ns, name, uid, rv, {
                "phase": PodPhase.RUNNING, "ready": False, "reason": "",
                "pod_ip": "127.0.0.1",
            })

        def to_ready():
            with self._lock:
                if self._seen.get(key) != uid:
                    return
            if tl.load is not None:
                tl.load.register(serve_key, key)
            self._mirror(ns, name, uid, 0,
                         {"phase": PodPhase.RUNNING, "ready": True})
            stats_tick()

        handles = []
        if not already_running:
            handles.append(self._wheel.schedule(tl.pending_s, to_running))
            handles.append(self._wheel.schedule(
                tl.pending_s + tl.serve_warmup_s, to_ready))
        else:
            # adopted mid-serve (restarted fleet): the model is loaded;
            # re-register and resume the stats stream after one warmup
            handles.append(self._wheel.schedule(tl.serve_warmup_s, to_ready))
        with self._lock:
            if self._seen.get(key) == uid and key in self._handles:
                self._handles[key].extend(handles)
            else:
                for h in handles:
                    _TimerWheel.cancel(h)

    def _mirror(self, ns: str, name: str, uid: str, rv: int,
                changes: Dict[str, Any]) -> None:
        """One status transition, through the real write machinery: the
        batcher (one patch-batch per agent tick, Conflict fallback with
        incarnation + write-once-terminal guards) or the direct
        uid-pinned ``patch_pod_status`` path."""
        if self._stop.is_set():
            return
        if self.status_sink is not None:
            self.status_sink.enqueue(ns, name, uid, rv, changes)
            return
        try:
            patch_pod_status(
                self.store, ns, name, uid, changes, expected_rv=rv,
                what="hollow-mirror",
            )
        except Exception:
            log.warning("hollow mirror of %s/%s failed", ns, name,
                        exc_info=True)


class HollowFleet:
    """N hollow nodes in one process (the kubemark cluster shape).

    Shared machinery instead of N× everything: ONE store watch routed to
    per-node executors by ``spec.node_name``, ONE timer wheel, ONE
    StatusBatcher, and a flusher that ships Node heartbeats (staggered
    round the interval) together with the dirty pod mirrors as CHUNKED
    patch-batch requests — store load is O(transitions + nodes/interval)
    requests regardless of pod count, which is what lets one host drive
    1k nodes / 100k pods against a real StoreServer.
    """

    def __init__(self, store, nodes: int, *,
                 name_prefix: str = "hollow-",
                 timeline: Optional[HollowTimeline] = None,
                 capacity_chips: int = 32,
                 advertise: str = "127.0.0.1",
                 heartbeat_interval: float = 10.0,
                 batch_items: int = 256,
                 maintenance: Optional[MaintenanceSchedule] = None,
                 clock: Any = None):
        from mpi_operator_tpu.executor.agent import StatusBatcher

        self.store = store
        self.timeline = timeline or HollowTimeline()
        self.maintenance = maintenance
        self.capacity_chips = capacity_chips
        self.advertise = advertise
        self.heartbeat_interval = heartbeat_interval
        self.batch_items = batch_items
        # the scenario engine's time-scalable clock (VirtualClock duck
        # type: to_wall(virtual_s)); when set, MaintenanceSchedule knobs
        # are read as SCENARIO seconds — a 6-hour wave compresses into a
        # minutes-long deterministic run instead of a wall-clock one
        self.clock = clock
        self.node_names = [f"{name_prefix}{i:04d}" for i in range(nodes)]
        self._wake = threading.Event()
        self.batcher = StatusBatcher(on_dirty=self._wake.set)
        self.wheel = _TimerWheel(clock=clock)
        self.executors: Dict[str, HollowExecutor] = {
            name: HollowExecutor(
                store, node_name=name, timeline=self.timeline,
                status_sink=self.batcher, wheel=self.wheel,
                external_events=True,
            )
            for name in self.node_names
        }
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._watch_q = None
        # node → next heartbeat due (monotonic), staggered across the
        # interval so 1k nodes do not beat in one thundering tick
        self._hb_due: Dict[str, float] = {}
        self.stats = {"heartbeats": 0, "mirrors": 0, "batches": 0}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HollowFleet":
        self.wheel.start()
        for ex in self.executors.values():
            ex.start()  # external_events: no watch, just arms the wheel
        self._register_nodes()
        now = time.monotonic()
        n = max(1, len(self.node_names))
        for i, name in enumerate(self.node_names):
            self._hb_due[name] = now + (i / n) * self.heartbeat_interval
        self._watch_q = self.store.watch(None)
        pump = threading.Thread(
            target=self._pump, name="hollow-fleet-pump", daemon=True
        )
        flush = threading.Thread(
            target=self._flush_loop, name="hollow-fleet-flush", daemon=True
        )
        pump.start()
        flush.start()
        self._threads += [pump, flush]
        # adopt pods bound before the watch began
        for pod in self.store.list("Pod"):
            ex = self.executors.get(pod.spec.node_name or "")
            if ex is not None:
                ex.observe(pod)
        if self.maintenance is not None:
            self.arm_maintenance(self.maintenance)
        log.info("hollow fleet up: %d nodes, %d chips each",
                 len(self.node_names), self.capacity_chips)
        return self

    def arm_maintenance(self, sched: MaintenanceSchedule) -> None:
        """Schedule the rolling notice wave on the shared timer wheel
        (``start_s`` counts from THIS call — benches arm it once the
        workload is live instead of at fleet start). With a scenario
        ``clock``, every schedule knob — start, stagger, AND the notice
        window itself — is scenario time: the wave's shape is invariant
        under ``--time-scale``, which is what makes compressed multi-hour
        soaks deterministic."""
        for i, name in enumerate(sched.victims(self.node_names)):
            delay = sched.start_s + i * sched.stagger_s

            def fire(node=name, notice=sched.notice_s):
                wall_notice = (self.clock.to_wall(notice)
                               if self.clock is not None else notice)
                try:
                    self.announce_maintenance(node,
                                              time.time() + wall_notice)
                except Exception:
                    log.warning("maintenance notice for %s failed", node,
                                exc_info=True)

            self.wheel.schedule(delay, fire, virtual=True)

    def announce_maintenance(self, node: str, at_ts: float) -> None:
        """Stamp the maintenance-notice annotation (the cloud provider's
        'this host dies at T' event, as the disruption plane consumes it).
        Metadata patch → needs an admin-tier store handle."""
        self.store.patch(
            "Node", NODE_NAMESPACE, node,
            {"metadata": {"annotations": {
                ANNOTATION_MAINTENANCE_AT: str(at_ts),
            }}},
        )
        log.info("maintenance notice: node %s dies at %.0f", node, at_ts)

    def kill_node(self, name: str) -> None:
        """Drop one hollow node dead, mid-flight (the spot-reclaim /
        host-loss fault): its executor stops (every armed pod transition
        cancelled — the 'processes' die with the host), its heartbeats
        cease (the monitor will see it go stale), and events are no
        longer routed to it. The Node object is NOT deleted and nothing
        is mirrored — a reclaimed host does not get to say goodbye; the
        control plane must notice on its own."""
        ex = self.executors.pop(name, None)
        if ex is None:
            raise KeyError(f"no hollow node {name!r} in this fleet")
        self._hb_due.pop(name, None)
        ex.stop()
        log.warning("hollow node %s killed (no further heartbeats)", name)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._watch_q is not None:
            self.store.stop_watch(self._watch_q)
        for t in self._threads:
            t.join(timeout=5.0)
        for ex in self.executors.values():
            ex.stop()
        self.wheel.stop()

    # -- internals -----------------------------------------------------------

    def _node_status(self, name: str) -> Dict[str, Any]:
        return {
            "address": self.advertise,
            "capacity_chips": self.capacity_chips,
            "ready": True,
            "last_heartbeat": time.time(),
        }

    def _register_nodes(self) -> None:
        for name in self.node_names:
            node = Node()
            node.metadata.namespace = NODE_NAMESPACE
            node.metadata.name = name
            node.status.address = self.advertise
            node.status.capacity_chips = self.capacity_chips
            node.status.ready = True
            node.status.last_heartbeat = time.time()
            try:
                self.store.create(node)
            except AlreadyExists:
                # restarted fleet: the first heartbeat patch refreshes it
                pass

    def _pump(self) -> None:
        import queue

        while not self._stop.is_set():
            try:
                ev = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if ev.kind != "Pod":
                continue
            try:
                # oplint: disable=LEV001 — the hollow kubelet is an
                # edge-driven simulator routing each delivery to the
                # executor that owns its node; on a DELETED edge the
                # object is already gone, so the delivered payload is the
                # ONLY place node_name still exists (a re-read would 404
                # and strand the teardown)
                ex = self.executors.get(ev.obj.spec.node_name or "")
                if ex is not None:
                    ex.handle_event(ev)
            except Exception:
                log.exception("hollow fleet routing failed; continuing")

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.1)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._flush_once()
            except Exception:
                # store briefly unreachable past the client's retry window:
                # mirrors were requeued, heartbeats re-due next pass
                log.warning("hollow fleet flush failed; retrying",
                            exc_info=True)

    def _flush_once(self) -> None:
        now = time.monotonic()
        hb_nodes = [n for n, due in self._hb_due.items() if due <= now]
        entries = self.batcher.drain()
        if not hb_nodes and not entries:
            return
        # (wire item, originating batcher entry | None-for-heartbeats)
        tagged: List[tuple] = []
        for n in hb_nodes:
            self._hb_due[n] = now + self.heartbeat_interval
            tagged.append(({
                "kind": "Node", "namespace": NODE_NAMESPACE, "name": n,
                "subresource": "status",
                "patch": {"status": self._node_status(n)},
            }, None))
        for e in entries:
            patch: Dict[str, Any] = {"status": e["changes"]}
            if e["rv"]:
                patch["metadata"] = {"resource_version": e["rv"]}
            tagged.append(({
                "kind": "Pod", "namespace": e["namespace"],
                "name": e["name"], "subresource": "status", "patch": patch,
            }, e))
        self.stats["heartbeats"] += len(hb_nodes)
        self.stats["mirrors"] += len(entries)
        # chunked: one giant 100k-item batch would stall the store's
        # handler (and every other tenant) for its whole apply
        for ofs in range(0, len(tagged), self.batch_items):
            chunk = tagged[ofs:ofs + self.batch_items]
            self.stats["batches"] += 1
            try:
                results = self.store.patch_batch([it for it, _ in chunk])
            except Exception:
                # the REQUEST failed: nothing in this or later chunks
                # committed — requeue their mirrors for the next pass and
                # re-due EVERY heartbeat this pass claimed (it was marked
                # sent before the wire attempt; leaving it for a full
                # interval could flap the node past the monitor's grace —
                # a redundant re-send is an idempotent status patch)
                self.batcher.requeue(
                    [e for _, e in tagged[ofs:] if e is not None]
                )
                for n in hb_nodes:
                    self._hb_due[n] = now
                raise
            for (_item, e), res in zip(chunk, results):
                if e is None:
                    continue  # heartbeat misses self-heal next beat
                self._settle_pod(e, res)

    def _settle_pod(self, e: Dict[str, Any], res: Any) -> None:
        """Per-item result handling — the NodeAgent._tick contract:
        Conflict → guarded re-read via patch_pod_status (incarnation +
        write-once-terminal checks), NotFound → the pod is gone, forget
        its anchor."""
        try:
            if isinstance(res, Conflict):
                committed = patch_pod_status(
                    self.store, e["namespace"], e["name"], e["uid"],
                    e["changes"], what="hollow-fleet-mirror",
                )
                if committed is not None:
                    self.batcher.note_committed(e, committed)
            elif isinstance(res, NotFound):
                self.batcher.forget(e["namespace"], e["name"])
            elif isinstance(res, Exception):
                log.warning("hollow mirror of %s/%s rejected: %s",
                            e["namespace"], e["name"], res)
            else:
                self.batcher.note_committed(e, res)
        except Exception:
            self.batcher.requeue([e])
            raise


class HollowNodeTarget:
    """One hollow node as a chaos process target (the ``targets=`` duck
    type ChaosController kills): ``reclaim``/``maintenance-fire`` against
    a hollow fleet SIGKILL nothing — they call :meth:`HollowFleet.
    kill_node`, which is the same observable event (heartbeats stop,
    armed pod transitions die) without a process to kill."""

    def __init__(self, fleet: HollowFleet, node: str):
        self.fleet = fleet
        self.node = node

    def kill(self) -> None:
        self.fleet.kill_node(self.node)

    def term(self) -> None:
        self.kill()

    def restart(self) -> None:
        raise RuntimeError("a reclaimed hollow node does not come back")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpu-hollow-fleet",
        description="Simulate N hollow nodes against a live store "
                    "(kubemark for the TPU control plane).",
    )
    ap.add_argument("--store", required=True,
                    help="the shared store ('http://HOST:PORT')")
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--chips", type=int, default=32)
    ap.add_argument("--prefix", default="hollow-")
    ap.add_argument("--heartbeat", type=float, default=10.0)
    ap.add_argument("--run-s", type=float, default=0.5,
                    help="scripted Running duration per pod")
    ap.add_argument("--failure-rate", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-items", type=int, default=128,
                    help="max patches per batch request flush")
    ap.add_argument("--maintenance-fraction", type=float, default=0.0,
                    help="fraction of the fleet that receives a seeded "
                         "rolling maintenance notice (0 = none)")
    ap.add_argument("--maintenance-notice", type=float, default=10.0,
                    help="seconds of warning each notice carries before "
                         "its deadline")
    ap.add_argument("--maintenance-start", type=float, default=5.0,
                    help="seconds after fleet start the first notice fires")
    ap.add_argument("--maintenance-stagger", type=float, default=0.5,
                    help="seconds between successive notices (the wave)")
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="scenario seconds per wall second (>1 compresses "
                         "the maintenance wave: its knobs are read as "
                         "SCENARIO time, so a multi-hour wave replays "
                         "deterministically in minutes)")
    ap.add_argument("--token-file", default=None)
    ap.add_argument("--monitoring-port", type=int, default=None,
                    help="serve /metrics + /healthz on this port (agent "
                         "tick latency etc. — the SLO monitor scrapes the "
                         "fleet process like any other); default: off")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    from mpi_operator_tpu.machinery.http_store import (
        HttpStoreClient,
        read_token_file,
    )

    # a generous request timeout: one chunked flush against a store busy
    # with a 10k-job storm may legitimately take several seconds
    store = HttpStoreClient(args.store, timeout=60.0,
                            token=read_token_file(args.token_file))
    clock = None
    if args.time_scale != 1.0:
        from mpi_operator_tpu.machinery.scenario import VirtualClock

        clock = VirtualClock(scale=args.time_scale)
    fleet = HollowFleet(
        store, args.nodes, name_prefix=args.prefix,
        timeline=HollowTimeline(run_s=args.run_s,
                                failure_rate=args.failure_rate,
                                seed=args.seed),
        capacity_chips=args.chips, heartbeat_interval=args.heartbeat,
        batch_items=args.batch_items, clock=clock,
        maintenance=(
            MaintenanceSchedule(
                fraction=args.maintenance_fraction,
                notice_s=args.maintenance_notice,
                start_s=args.maintenance_start,
                stagger_s=args.maintenance_stagger,
                seed=args.seed,
            )
            if args.maintenance_fraction > 0 else None
        ),
    ).start()
    ops = None
    if args.monitoring_port is not None:
        from mpi_operator_tpu.opshell.server import OpsServer

        ops = OpsServer(args.monitoring_port)
        ops.start()
        logging.info("metrics on :%d/metrics", ops.port)
    print(f"hollow fleet of {args.nodes} nodes running", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    if ops is not None:
        ops.stop()
    fleet.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
