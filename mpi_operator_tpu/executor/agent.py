"""NodeAgent: the per-node execution agent (the kubelet role).

The reference's controller only *creates* pods
(/root/reference/v2/pkg/controller/mpi_job_controller.go:817-877,1246-1296);
kubernetes' kubelet — one per node — is what actually runs an MPIJob's
workers on N machines and feeds their status back. This process is that
component for this framework:

- connects to the shared store (normally ``--store http://...``, the
  etcd/apiserver seam of machinery/http_store.py),
- **claims only pods whose ``spec.node_name`` matches its identity**
  (the binding the gang scheduler wrote), runs them through the
  LocalExecutor process machinery, and mirrors phases back,
- registers itself as a :class:`Node` object and **heartbeats** it, so the
  leader's NodeMonitor can evict pods off a dead node (≙ the node
  controller's pod eviction),
- serves its pods' log files over HTTP and stamps *URLs* (not local paths)
  into ``pod.status.log_path``, so ``ctl logs`` works from any node
  (≙ ``kubectl logs`` riding the kubelet API),
- resolves coordinator addresses through the store: worker-0's pod →
  its bound node → that node's advertised address (the headless-service
  DNS role).

Deployed as the DaemonSet-shaped second deployment of
deploy/overlays/cluster (one per execution node):

  python -m mpi_operator_tpu.executor.agent \\
      --store http://store:8475 --token-file /etc/tpujob/token \\
      --node-name slice0/0x0 --advertise 10.0.0.7
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from mpi_operator_tpu.executor.local import LocalExecutor
from mpi_operator_tpu.machinery.objects import (
    NODE_NAMESPACE,
    Node,
    PodPhase,
    evict_pod,
)
from mpi_operator_tpu.machinery.store import NotFound

log = logging.getLogger("tpujob.agent")

# largest single /logs response (clients loop on ?offset= for the rest)
MAX_LOG_CHUNK = 8 << 20


class LogServer:
    """Serves the agent's log directory read-only over HTTP.

    GET /logs/<file> streams one pod log (basenames only — the executor
    names files uniquely per pod incarnation; traversal is rejected).
    ``?offset=N`` returns only bytes from N (the `ctl logs --follow`
    incremental-fetch contract, ≙ the kubelet's follow streaming).

    When ``tokens`` is configured, every /logs request must present one of
    them as a bearer token (training logs can contain data samples).
    The accepted set is whatever the agent was HANDED — its own store
    token (shared admin, or its node-scoped credential) plus the read
    token. In agent-scoped deployments the admin token is deliberately
    absent from execution nodes, so log fetches use the READ token
    (`ctl --read-token-file`); that is also the least-privilege practice,
    since this endpoint is plain HTTP. /healthz stays open for probes.
    """

    def __init__(self, logs_dir: str, host: str = "0.0.0.0", port: int = 0,
                 tokens: Optional[Sequence[str]] = None):
        self.logs_dir = logs_dir
        self.tokens = [t for t in (tokens or []) if t]
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # idle/half-open keep-alive connections must not pin handler
            # threads forever: an agent OOM from unbounded thread growth
            # would PDEATHSIG-kill every worker on the node (same guard as
            # the store server's handler)
            timeout = 65.0

            def log_message(self, fmt, *args):  # quiet
                pass

            def _authorized(self) -> bool:
                if not server.tokens:
                    return True
                from mpi_operator_tpu.machinery.http_store import check_bearer

                return check_bearer(
                    self.headers.get("Authorization", ""), server.tokens
                ) is not None

            def do_GET(self):
                if self.path == "/healthz":
                    body = b'{"ok": true}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if not self._authorized():
                    body = (b'{"error": "Unauthorized", "message": '
                            b'"missing or invalid bearer token"}')
                    self.send_response(401)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                import urllib.parse as _up

                parsed = _up.urlparse(self.path)
                prefix = "/logs/"
                name = (parsed.path[len(prefix):]
                        if parsed.path.startswith(prefix) else "")
                # basenames only: no separators, no traversal
                if not name or "/" in name or "\\" in name or ".." in name:
                    self.send_error(404)
                    return
                try:
                    offset = max(
                        0, int(_up.parse_qs(parsed.query).get("offset", ["0"])[0])
                    )
                except ValueError:
                    self.send_error(400)
                    return
                path = os.path.join(server.logs_dir, name)
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        # bounded per response: a multi-GB training log must
                        # not be materialized in the agent's RAM (an OOM here
                        # would PDEATHSIG-kill every worker on the node);
                        # clients loop on ?offset= until an empty read
                        data = f.read(MAX_LOG_CHUNK)
                except OSError:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="agent-logs", daemon=True
        )

    def start(self) -> "LogServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class NodeAgent:
    """One node's claim-run-report loop + Node heartbeat."""

    def __init__(
        self,
        store,
        node_name: str,
        *,
        advertise: str = "127.0.0.1",
        capacity_chips: Optional[int] = None,
        logs_dir: Optional[str] = None,
        log_port: int = 0,
        workdir: Optional[str] = None,
        heartbeat_interval: float = 2.0,
        log_tokens: Optional[Sequence[str]] = None,
        ckpt_dir: Optional[str] = None,
    ):
        from mpi_operator_tpu.machinery.objects import LOCAL_NODE

        if node_name == LOCAL_NODE:
            # 'local' is the scheduler's single-process sentinel binding;
            # an agent claiming it would collide with the require_nodes
            # healer (which unbinds PENDING 'local' pods every pass) and
            # with any co-resident LocalExecutor
            raise ValueError(
                f"--node-name {node_name!r} is reserved (the scheduler's "
                f"single-process sentinel); pick any other identity"
            )
        self.store = store
        self.node_name = node_name
        self.advertise = advertise
        self.capacity_chips = capacity_chips
        self.heartbeat_interval = heartbeat_interval
        self.logs_dir = logs_dir or tempfile.mkdtemp(prefix="tpujob-agent-logs-")
        self.log_server = LogServer(self.logs_dir, port=log_port,
                                    tokens=log_tokens)
        # the shared checkpoint volume's mount point ON THIS NODE: exported
        # to every pod as TPUJOB_CKPT_DIR so workloads derive per-job
        # checkpoint paths that survive the gang being re-placed onto other
        # nodes (bootstrap.default_checkpoint_dir)
        self.ckpt_dir = ckpt_dir
        extra_env = {}
        if ckpt_dir:
            from mpi_operator_tpu.runtime.bootstrap import ENV_CKPT_DIR

            extra_env[ENV_CKPT_DIR] = ckpt_dir
        self.executor = LocalExecutor(
            store,
            require_binding=True,
            node_name=node_name,
            logs_dir=self.logs_dir,
            workdir=workdir,
            extra_env=extra_env,
            log_url_base=None,  # filled at start (needs the bound log port)
        )
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # -- node object ---------------------------------------------------------

    def _node_template(self) -> Node:
        node = Node()
        node.metadata.namespace = NODE_NAMESPACE
        node.metadata.name = self.node_name
        node.status.address = self.advertise
        node.status.log_url = f"http://{self.advertise}:{self.log_server.port}/logs"
        node.status.capacity_chips = self.capacity_chips
        node.status.ready = True
        node.status.last_heartbeat = time.time()
        return node

    def _register(self) -> None:
        from mpi_operator_tpu.machinery.store import Conflict

        tmpl = self._node_template()
        for _ in range(5):
            if self._stop.is_set():
                # stop() force-marks ready=False; a beat retrying past that
                # would resurrect a Ready record for a dead agent and make
                # the monitor burn the full grace window
                return
            try:
                cur = self.store.get("Node", NODE_NAMESPACE, self.node_name)
            except NotFound:
                self.store.create(tmpl)
                return
            # the cordon flag belongs to the operator (`ctl cordon/drain`),
            # not to this agent: a heartbeat must never un-cordon the node.
            # Optimistic update (NOT force): a cordon committed between our
            # read and write raises Conflict and we re-read — a forced write
            # would silently resurrect the stale uncordoned copy.
            tmpl.status.unschedulable = cur.status.unschedulable
            cur.status = tmpl.status
            try:
                self.store.update(cur)
                return
            except Conflict:
                continue
        log.warning("heartbeat lost a conflict race 5x; next beat retries")

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._register()  # create-or-refresh: survives node deletion
            except Exception:
                # store briefly unreachable: keep trying — the monitor's
                # grace period absorbs short gaps
                log.warning("heartbeat failed; retrying", exc_info=True)

    def _evict_orphans(self) -> None:
        """A restarted agent lost its child processes: any pod the store
        still shows RUNNING on this node has no process behind it — mark it
        evicted so the controller's gang-coherent restart recovers it
        (the kubelet-restart reconciliation)."""
        for pod in self.store.list("Pod"):
            if pod.spec.node_name != self.node_name:
                continue
            if pod.status.phase != PodPhase.RUNNING:
                continue
            evict_pod(self.store, pod, "node agent restarted; process lost")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "NodeAgent":
        self.log_server.start()
        self.executor.log_url_base = (
            f"http://{self.advertise}:{self.log_server.port}/logs"
        )
        self._register()
        self._evict_orphans()
        self.executor.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="agent-heartbeat", daemon=True
        )
        self._hb_thread.start()
        log.info(
            "node agent %s up (advertise %s, logs :%d)",
            self.node_name, self.advertise, self.log_server.port,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        self.executor.stop()
        try:
            from mpi_operator_tpu.machinery.store import optimistic_update

            def mutate(cur) -> bool:
                cur.status.ready = False
                return True

            # optimistic, not force: node-scoped credentials forbid force,
            # and a concurrent cordon must not be clobbered
            optimistic_update(
                self.store, "Node", NODE_NAMESPACE, self.node_name, mutate,
                what="agent-stop",
            )
        except Exception:
            pass  # best-effort drain mark; the monitor catches it anyway
        self.log_server.stop()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpu-node-agent", description=__doc__)
    ap.add_argument("--store", required=True,
                    help="the shared store ('http://HOST:PORT' across nodes; "
                         "'sqlite:PATH' for same-host testing)")
    ap.add_argument("--token-file", default=None,
                    help="ADMIN bearer token file: presented to an "
                         "authenticated http store, and accepted on this "
                         "agent's log endpoint when configured")
    ap.add_argument("--read-token-file", default=None,
                    help="READ-ONLY bearer token file: additionally accepted "
                         "on the log endpoint (so view-tier `ctl logs` "
                         "works); never presented to the store")
    ap.add_argument("--node-name", required=True,
                    help="this node's identity — must match what the "
                         "scheduler binds (inventory mode: e.g. slice0/0x0)")
    ap.add_argument("--advertise", default="127.0.0.1",
                    help="address other nodes reach this node at "
                         "(coordinator rendezvous + log fetch)")
    ap.add_argument("--chips", type=int, default=None,
                    help="chip capacity for scalar-mode node scheduling "
                         "(default: unbounded)")
    ap.add_argument("--logs-dir", default=None)
    ap.add_argument("--ckpt-dir", default=None,
                    help="node-local mount point of the cluster's SHARED "
                         "checkpoint volume (exported to pods as "
                         "TPUJOB_CKPT_DIR; workloads derive "
                         "<dir>/<namespace>/<job> from it so a restarted "
                         "gang re-placed onto other nodes resumes from the "
                         "same path)")
    ap.add_argument("--log-port", type=int, default=0,
                    help="port for the log endpoint (default: ephemeral)")
    ap.add_argument("--heartbeat", type=float, default=2.0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--tls-ca-file", default=None,
                    help="CA bundle (or the self-signed cert itself) to "
                         "verify a --store https://... against")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from mpi_operator_tpu.machinery.http_store import read_token_file
    from mpi_operator_tpu.opshell.__main__ import build_store

    if args.store == "memory":
        print("error: --store memory is private to one process; an agent "
              "needs the shared store the scheduler binds into",
              file=sys.stderr)
        return 2
    try:
        token = read_token_file(args.token_file)
        read_token = read_token_file(args.read_token_file)
    except (OSError, ValueError) as e:
        print(f"error: token file: {e}", file=sys.stderr)
        return 2
    if read_token is not None and token is None:
        # same fail-closed posture as tpu-store and tpu-operator: a read
        # tier without the admin tier means an unauthenticated store
        # connection nobody asked for
        print("error: --read-token-file requires --token-file "
              "(the admin tier anchors auth)", file=sys.stderr)
        return 2
    store = build_store(args.store, token=token, ca_file=args.tls_ca_file)
    try:
        agent = NodeAgent(
            store,
            args.node_name,
            advertise=args.advertise,
            capacity_chips=args.chips,
            logs_dir=args.logs_dir,
            log_port=args.log_port,
            workdir=args.workdir,
            heartbeat_interval=args.heartbeat,
            log_tokens=[t for t in (token, read_token) if t],
            ckpt_dir=args.ckpt_dir,
        ).start()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"node agent {args.node_name} running "
          f"(logs http://{args.advertise}:{agent.log_server.port}/logs)",
          flush=True)
    stop = threading.Event()

    def on_signal(sig, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    agent.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
