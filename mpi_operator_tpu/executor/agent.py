"""NodeAgent: the per-node execution agent (the kubelet role).

The reference's controller only *creates* pods
(/root/reference/v2/pkg/controller/mpi_job_controller.go:817-877,1246-1296);
kubernetes' kubelet — one per node — is what actually runs an MPIJob's
workers on N machines and feeds their status back. This process is that
component for this framework:

- connects to the shared store (normally ``--store http://...``, the
  etcd/apiserver seam of machinery/http_store.py),
- **claims only pods whose ``spec.node_name`` matches its identity**
  (the binding the gang scheduler wrote), runs them through the
  LocalExecutor process machinery, and mirrors phases back,
- registers itself as a :class:`Node` object and **heartbeats** it, so the
  leader's NodeMonitor can evict pods off a dead node (≙ the node
  controller's pod eviction). The heartbeat and every dirty pod-status
  mirror ride ONE ``patch_batch`` request per tick (StatusBatcher below):
  agent store load is O(1) per tick regardless of pod count, and the
  status-subresource patches fit the NODE token tier's patch-status-only
  grant,
- serves its pods' log files over HTTP and stamps *URLs* (not local paths)
  into ``pod.status.log_path``, so ``ctl logs`` works from any node
  (≙ ``kubectl logs`` riding the kubelet API),
- resolves coordinator addresses through the store: worker-0's pod →
  its bound node → that node's advertised address (the headless-service
  DNS role).

Deployed as the DaemonSet-shaped second deployment of
deploy/overlays/cluster (one per execution node):

  python -m mpi_operator_tpu.executor.agent \\
      --store http://store:8475 --token-file /etc/tpujob/token \\
      --node-name slice0/0x0 --advertise 10.0.0.7
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from mpi_operator_tpu.executor.local import LocalExecutor
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.objects import (
    NODE_NAMESPACE,
    Node,
    PodPhase,
    evict_pod,
    patch_pod_status,
)
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    Forbidden,
    NotFound,
    json_merge_patch,
)
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.agent")


class StatusBatcher:
    """Collects the executor's pod status mirrors between agent ticks so
    the heartbeat loop can flush them — together with the Node heartbeat —
    as ONE ``patch_batch`` request. This is the write-side answer to the
    O(workers × jobs) apiserver-load shape the reference's redesign
    proposal names (proposals/scalable-robust-operator.md:90-109): an
    agent's store traffic is O(1) per tick regardless of how many pods it
    runs.

    Entries coalesce per pod (a RUNNING mirror followed by the terminal
    mirror inside one tick merges, later keys winning — RFC 7386 over the
    status changes), and each carries the rv the executor believes current
    as the patch's precondition: a Conflict at flush time falls back to
    patch_pod_status's guarded re-read, which re-applies the incarnation
    and write-once-terminal guards exactly like the direct path.
    ``on_dirty`` (the agent's wake event) makes the flush prompt — the
    batch rides the next tick, the tick just happens immediately."""

    def __init__(self, on_dirty=None):
        self._lock = threading.Lock()
        # (namespace, name) → entry dict; insertion-ordered (flush order)
        self._entries: "dict" = {}
        # (namespace, name) → (uid, rv) of our last committed mirror: a
        # later mirror of the same incarnation (the reaper's terminal
        # write after our RUNNING commit) anchors its precondition here
        # instead of on its stale launch-time snapshot, keeping the flush
        # at one request. Dropped on terminal commit (the pod is done).
        self._committed: "dict" = {}
        self._on_dirty = on_dirty

    def enqueue(self, namespace, name, uid, rv, changes) -> None:
        key = (namespace, name)
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None and cur["uid"] == uid:
                # same incarnation: merge, keeping the FIRST rv anchor (the
                # store hasn't seen either write yet, so the precondition
                # must reference the pre-batch state)
                cur["changes"] = json_merge_patch(cur["changes"], changes)
            else:
                known = self._committed.get(key)
                if known is not None and known[0] == uid:
                    rv = max(rv, known[1])
                self._entries[key] = {
                    "namespace": namespace, "name": name, "uid": uid,
                    "rv": rv, "changes": dict(changes),
                }
        if self._on_dirty is not None:
            self._on_dirty()

    # anchor-memory bound: a long-lived agent churning through many pod
    # names must not grow _committed forever (forget() handles the normal
    # disappearances; this is the backstop — oldest entries drop first,
    # costing at worst one Conflict-and-re-read on their next mirror)
    _COMMITTED_CAP = 4096

    def note_committed(self, entry, committed) -> None:
        """Record a flush result (the committed pod) for rv anchoring."""
        key = (entry["namespace"], entry["name"])
        terminal = entry["changes"].get("phase") in (
            PodPhase.SUCCEEDED, PodPhase.FAILED,
        )
        with self._lock:
            if terminal:
                self._committed.pop(key, None)
            else:
                self._committed[key] = (
                    committed.metadata.uid,
                    committed.metadata.resource_version,
                )
                while len(self._committed) > self._COMMITTED_CAP:
                    self._committed.pop(next(iter(self._committed)))

    def forget(self, namespace, name) -> None:
        """Drop the rv anchor for a pod that disappeared without a local
        terminal commit (deleted by gang cleanup, rebound out of scope) —
        the counterpart of LocalExecutor._forget's _status_rv cleanup."""
        with self._lock:
            self._committed.pop((namespace, name), None)

    def drain(self):
        with self._lock:
            out = list(self._entries.values())
            self._entries.clear()
        return out

    def requeue(self, entries) -> None:
        """Put drained-but-unflushed entries back (the whole batch request
        failed — store unreachable past the client's retry window). An
        entry enqueued meanwhile for the same pod merges ON TOP of the
        requeued one: the requeued changes are the older state."""
        with self._lock:
            for e in entries:
                key = (e["namespace"], e["name"])
                cur = self._entries.get(key)
                if cur is not None and cur["uid"] == e["uid"]:
                    cur["changes"] = json_merge_patch(
                        e["changes"], cur["changes"]
                    )
                    cur["rv"] = e["rv"]  # the pre-batch anchor stands
                elif cur is None:
                    self._entries[key] = e
                # different uid: the pod was reincarnated while the store
                # was away — the old incarnation's mirror is moot

# largest single /logs response (clients loop on ?offset= for the rest)
MAX_LOG_CHUNK = 8 << 20


class LogServer:
    """Serves the agent's log directory read-only over HTTP.

    GET /logs/<file> streams one pod log (basenames only — the executor
    names files uniquely per pod incarnation; traversal is rejected).
    ``?offset=N`` returns only bytes from N (the `ctl logs --follow`
    incremental-fetch contract, ≙ the kubelet's follow streaming).

    When ``tokens`` is configured, every /logs request must present one of
    them as a bearer token (training logs can contain data samples).
    The accepted set is whatever the agent was HANDED — its own store
    token (shared admin, or its node-scoped credential) plus the read
    token. In agent-scoped deployments the admin token is deliberately
    absent from execution nodes, so log fetches use the READ token
    (`ctl --read-token-file`); that is also the least-privilege practice,
    since this endpoint is plain HTTP. /healthz stays open for probes.
    """

    def __init__(self, logs_dir: str, host: str = "0.0.0.0", port: int = 0,
                 tokens: Optional[Sequence[str]] = None):
        self.logs_dir = logs_dir
        self.tokens = [t for t in (tokens or []) if t]
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # idle/half-open keep-alive connections must not pin handler
            # threads forever: an agent OOM from unbounded thread growth
            # would PDEATHSIG-kill every worker on the node (same guard as
            # the store server's handler)
            timeout = 65.0

            def log_message(self, fmt, *args):  # quiet
                pass

            def _authorized(self) -> bool:
                if not server.tokens:
                    return True
                from mpi_operator_tpu.machinery.http_store import check_bearer

                return check_bearer(
                    self.headers.get("Authorization", ""), server.tokens
                ) is not None

            def do_GET(self):
                if self.path == "/healthz":
                    body = b'{"ok": true}'
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if not self._authorized():
                    body = (b'{"error": "Unauthorized", "message": '
                            b'"missing or invalid bearer token"}')
                    self.send_response(401)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                import urllib.parse as _up

                parsed = _up.urlparse(self.path)
                prefix = "/logs/"
                name = (parsed.path[len(prefix):]
                        if parsed.path.startswith(prefix) else "")
                # basenames only: no separators, no traversal
                if not name or "/" in name or "\\" in name or ".." in name:
                    self.send_error(404)
                    return
                try:
                    offset = max(
                        0, int(_up.parse_qs(parsed.query).get("offset", ["0"])[0])
                    )
                except ValueError:
                    self.send_error(400)
                    return
                path = os.path.join(server.logs_dir, name)
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        # bounded per response: a multi-GB training log must
                        # not be materialized in the agent's RAM (an OOM here
                        # would PDEATHSIG-kill every worker on the node);
                        # clients loop on ?offset= until an empty read
                        data = f.read(MAX_LOG_CHUNK)
                except OSError:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="agent-logs", daemon=True
        )

    def start(self) -> "LogServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class NodeAgent:
    """One node's claim-run-report loop + Node heartbeat."""

    def __init__(
        self,
        store,
        node_name: str,
        *,
        advertise: str = "127.0.0.1",
        capacity_chips: Optional[int] = None,
        logs_dir: Optional[str] = None,
        log_port: int = 0,
        workdir: Optional[str] = None,
        heartbeat_interval: float = 2.0,
        log_tokens: Optional[Sequence[str]] = None,
        ckpt_dir: Optional[str] = None,
        eviction_grace: float = 5.0,
        hollow=None,
    ):
        from mpi_operator_tpu.machinery.objects import LOCAL_NODE

        if node_name == LOCAL_NODE:
            # 'local' is the scheduler's single-process sentinel binding;
            # an agent claiming it would collide with the require_nodes
            # healer (which unbinds PENDING 'local' pods every pass) and
            # with any co-resident LocalExecutor
            raise ValueError(
                f"--node-name {node_name!r} is reserved (the scheduler's "
                f"single-process sentinel); pick any other identity"
            )
        self.store = store
        self.node_name = node_name
        self.advertise = advertise
        self.capacity_chips = capacity_chips
        self.heartbeat_interval = heartbeat_interval
        self.logs_dir = logs_dir or tempfile.mkdtemp(prefix="tpujob-agent-logs-")
        self.log_server = LogServer(self.logs_dir, port=log_port,
                                    tokens=log_tokens)
        # the shared checkpoint volume's mount point ON THIS NODE: exported
        # to every pod as TPUJOB_CKPT_DIR so workloads derive per-job
        # checkpoint paths that survive the gang being re-placed onto other
        # nodes (bootstrap.default_checkpoint_dir)
        self.ckpt_dir = ckpt_dir
        extra_env = {}
        if ckpt_dir:
            from mpi_operator_tpu.runtime.bootstrap import ENV_CKPT_DIR

            extra_env[ENV_CKPT_DIR] = ckpt_dir
        # wake-driven flush: pod mirrors enqueue here and set the wake
        # event, so the batch rides an immediate tick instead of waiting
        # out the heartbeat interval (prompt transitions, still 1 request)
        self._wake = threading.Event()
        self.batcher = StatusBatcher(on_dirty=self._wake.set)
        if hollow is not None:
            # kubemark mode (--hollow): the REAL agent loop — watch, bind
            # pickup, heartbeats, one patch-batch per tick — over scripted
            # phase transitions instead of process launches, so one host
            # can stand in for a whole fleet (executor/hollow.py)
            from mpi_operator_tpu.executor.hollow import HollowExecutor

            self.executor = HollowExecutor(
                store,
                node_name=node_name,
                timeline=hollow,
                status_sink=self.batcher,
                logs_dir=self.logs_dir,
            )
        else:
            self.executor = LocalExecutor(
                store,
                require_binding=True,
                node_name=node_name,
                logs_dir=self.logs_dir,
                workdir=workdir,
                extra_env=extra_env,
                log_url_base=None,  # filled at start (needs bound log port)
                status_sink=self.batcher,
                eviction_grace=eviction_grace,
            )
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # -- node object ---------------------------------------------------------

    def _node_template(self) -> Node:
        node = Node()
        node.metadata.namespace = NODE_NAMESPACE
        node.metadata.name = self.node_name
        node.status.address = self.advertise
        node.status.log_url = f"http://{self.advertise}:{self.log_server.port}/logs"
        node.status.capacity_chips = self.capacity_chips
        node.status.ready = True
        node.status.last_heartbeat = time.time()
        return node

    def _heartbeat_status(self) -> dict:
        """The Node status fields a heartbeat refreshes, as a merge-patch
        value. ``unschedulable`` is deliberately ABSENT: the cordon flag is
        operator-owned and a merge-patch leaves untouched keys alone — the
        old GET+PUT loop had to copy the flag forward and retry Conflicts
        to get the same guarantee (and the store server 403s the key for
        NODE-tier credentials outright)."""
        s = self._node_template().status.to_dict()
        s.pop("unschedulable", None)
        return s

    def _register(self) -> None:
        """Create-or-refresh this agent's Node: ONE status-subresource
        patch when it exists (the steady-state beat — no GET leg, no
        conflict loop, cordon preserved by construction), create when it
        does not (first start, or the Node was deleted out from under
        us)."""
        if self._stop.is_set():
            # stop() marks ready=False; a beat racing past that would
            # resurrect a Ready record for a dead agent and make the
            # monitor burn the full grace window
            return
        try:
            self.store.patch(
                "Node", NODE_NAMESPACE, self.node_name,
                {"status": self._heartbeat_status()}, subresource="status",
            )
            return
        except NotFound:
            pass
        try:
            self.store.create(self._node_template())
        except AlreadyExists:
            # raced another registration of the same identity: the next
            # beat's patch lands on whichever copy won
            log.warning("node registration raced; next beat refreshes")

    def _tick(self) -> None:
        """One agent tick = ONE store round-trip: the Node heartbeat plus
        every dirty pod-status mirror the executor enqueued since the last
        tick, shipped as a single patch_batch. Per-item failures are
        handled item-by-item (a deleted pod must not cost the heartbeat):
        Conflict falls back to patch_pod_status's guarded re-read (the
        same incarnation/write-once checks as the direct path), NotFound
        on the Node recreates it."""
        if self._stop.is_set():
            return  # stop() owns the final (ready=False) write
        entries = self.batcher.drain()
        items = [{
            "kind": "Node", "namespace": NODE_NAMESPACE,
            "name": self.node_name, "subresource": "status",
            "patch": {"status": self._heartbeat_status()},
        }]
        for e in entries:
            patch = {"status": e["changes"]}
            if e["rv"]:
                patch["metadata"] = {"resource_version": e["rv"]}
            items.append({
                "kind": "Pod", "namespace": e["namespace"], "name": e["name"],
                "subresource": "status", "patch": patch,
            })
        try:
            results = self.store.patch_batch(items)
        except Forbidden as denial:
            # authz fails the whole batch when ANY item is out of scope —
            # e.g. a stale mirror for a pod that was deleted and recreated
            # UNBOUND under the same name (the new incarnation is not ours
            # to patch, and rightly so). Degrade this tick to per-item
            # writes: the heartbeat and every legitimate mirror land, and
            # only the entries authz genuinely denies are dropped (their
            # pod is not ours anymore; the mirror is moot).
            log.warning("batch rejected (%s); retrying per-item", denial)
            try:
                self._register()
            except Exception:
                self.batcher.requeue(entries)  # nothing flushed yet
                raise
            for i, e in enumerate(entries):
                try:
                    committed = patch_pod_status(
                        self.store, e["namespace"], e["name"], e["uid"],
                        e["changes"], expected_rv=e["rv"],
                        what="agent-mirror",
                    )
                    if committed is not None:
                        self.batcher.note_committed(e, committed)
                except Forbidden as fe:
                    log.warning(
                        "dropping out-of-scope mirror %s/%s: %s",
                        e["namespace"], e["name"], fe,
                    )
                    self.batcher.forget(e["namespace"], e["name"])
                except Exception:
                    # store went away mid-loop: keep the rest for next tick
                    self.batcher.requeue(entries[i:])
                    raise
            return
        except Exception:
            # the REQUEST failed (not an item): nothing committed — put the
            # mirrors back so the next tick retries them
            self.batcher.requeue(entries)
            raise
        node_res = results[0] if results else None
        if isinstance(node_res, NotFound):
            try:
                self._register()  # Node deleted out from under us: recreate
            except Exception:
                # re-registration died (store went away again): the pod
                # entries' Conflict fallbacks below haven't run — keep them
                # for the next tick (re-applying committed ones is
                # idempotent; terminal re-sends drop on the finished guard)
                self.batcher.requeue(entries)
                raise
        elif isinstance(node_res, Exception):
            log.warning("node heartbeat rejected: %s", node_res)
        pod_results = list(zip(entries, results[1:]))
        for i, (e, res) in enumerate(pod_results):
            try:
                if isinstance(res, Conflict):
                    committed = patch_pod_status(
                        self.store, e["namespace"], e["name"], e["uid"],
                        e["changes"], what="agent-mirror",
                    )
                    if committed is not None:
                        self.batcher.note_committed(e, committed)
                elif isinstance(res, NotFound):
                    # pod deleted (gang cleanup): nothing to mirror, and
                    # its rv anchor has nothing left to anchor
                    self.batcher.forget(e["namespace"], e["name"])
                elif isinstance(res, Exception):
                    log.warning(
                        "status mirror of %s/%s rejected: %s",
                        e["namespace"], e["name"], res,
                    )
                else:
                    self.batcher.note_committed(e, res)
            except Exception:
                # the store went away mid-fallback (past the client's
                # retry window): the mirror for THIS entry and every one
                # not yet processed must survive to the next tick — a
                # dropped terminal mirror would leave its pod RUNNING in
                # the store forever (the executor enqueues each transition
                # exactly once). Re-applying an already-committed patch on
                # retry is idempotent (same merge, conflict path re-reads).
                self.batcher.requeue([x for x, _ in pod_results[i:]])
                raise

    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.heartbeat_interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                # agent.tick spans are per-tick roots — parent=ROOT, not
                # the default None, which would inherit any span a bug
                # ever leaked open on this thread (a tick batches many
                # jobs' mirrors; job-scoped causality lives in the
                # executor launch/evict spans). The tick round-trip time
                # lands in the agent-tick histogram where the span closes.
                t0 = time.perf_counter()
                with trace.start_span(
                    "agent.tick", parent=trace.ROOT,
                    attrs={"node": self.node_name},
                ):
                    self._tick()
                metrics.agent_tick_latency.observe(time.perf_counter() - t0)
            except Exception:
                # store briefly unreachable past the client's own
                # retry/backoff window: keep trying — the monitor's grace
                # period absorbs short gaps, and the batcher re-coalesces
                # mirrors enqueued meanwhile
                log.warning("heartbeat tick failed; retrying", exc_info=True)

    def _evict_orphans(self) -> None:
        """A restarted agent lost its child processes: any pod the store
        still shows RUNNING on this node has no process behind it — mark it
        evicted so the controller's gang-coherent restart recovers it
        (the kubelet-restart reconciliation)."""
        for pod in self.store.list("Pod"):
            if pod.spec.node_name != self.node_name:
                continue
            if pod.status.phase != PodPhase.RUNNING:
                continue
            evict_pod(self.store, pod, "node agent restarted; process lost")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "NodeAgent":
        self.log_server.start()
        self.executor.log_url_base = (
            f"http://{self.advertise}:{self.log_server.port}/logs"
        )
        self._register()
        self._evict_orphans()
        self.executor.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="agent-heartbeat", daemon=True
        )
        self._hb_thread.start()
        log.info(
            "node agent %s up (advertise %s, logs :%d)",
            self.node_name, self.advertise, self.log_server.port,
        )
        return self

    def _drain_mirrors(self) -> None:
        """Flush every queued pod mirror synchronously (shutdown path —
        best-effort per entry; the monitor's eviction is the backstop)."""
        for e in self.batcher.drain():
            try:
                patch_pod_status(
                    self.store, e["namespace"], e["name"], e["uid"],
                    e["changes"], expected_rv=e["rv"], what="agent-drain",
                )
            except Exception:
                log.debug("shutdown mirror of %s/%s failed; the monitor's "
                          "eviction is the backstop", e["namespace"],
                          e["name"], exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()  # unblock the heartbeat loop promptly
        if self._hb_thread is not None:
            # wait out any in-flight tick BEFORE the shutdown writes: a
            # stalled tick (store restarting, client mid-backoff) could
            # otherwise commit ready=True AFTER our final ready=False —
            # resurrecting a heartbeat for a dead agent — or requeue its
            # failed batch's mirrors after the drain below already ran,
            # stranding them forever. Bounded: a tick blocks at most the
            # client's request timeout plus its conn-refused backoff.
            self._hb_thread.join(timeout=15.0)
        self.executor.stop()
        # the stop just killed every child process; their reapers enqueue
        # terminal mirrors into the batcher, whose flusher is exiting —
        # drain them synchronously so killed pods are marked Failed NOW
        # (the old direct-write path did this implicitly; leaving them
        # RUNNING would stall the gang restart for the monitor's whole
        # heartbeat grace window)
        self.executor.join_reapers(timeout=2.0)
        self._drain_mirrors()
        if self._hb_thread is not None and self._hb_thread.is_alive():
            # a degraded tick (one request per entry against a slow store)
            # can outlive the first join: wait it out and sweep whatever
            # its failure path requeued after our drain. If the store is
            # down hard even past this, the monitor's heartbeat-grace
            # eviction is the documented backstop.
            self._hb_thread.join(timeout=30.0)
            self._drain_mirrors()
        try:
            # one unconditional status patch: the cordon flag is untouched
            # by construction (merge semantics), and NODE-tier credentials
            # are allowed exactly this write
            self.store.patch(
                "Node", NODE_NAMESPACE, self.node_name,
                {"status": {"ready": False}}, subresource="status",
            )
        except Exception:
            # best-effort drain mark; the monitor catches it anyway
            log.debug("final ready=False mark failed", exc_info=True)
        self.log_server.stop()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpu-node-agent", description=__doc__)
    ap.add_argument("--store", required=True,
                    help="the shared store ('http://HOST:PORT' across nodes; "
                         "'sqlite:PATH' for same-host testing)")
    ap.add_argument("--token-file", default=None,
                    help="ADMIN bearer token file: presented to an "
                         "authenticated http store, and accepted on this "
                         "agent's log endpoint when configured")
    ap.add_argument("--read-token-file", default=None,
                    help="READ-ONLY bearer token file: additionally accepted "
                         "on the log endpoint (so view-tier `ctl logs` "
                         "works); never presented to the store")
    ap.add_argument("--node-name", required=True,
                    help="this node's identity — must match what the "
                         "scheduler binds (inventory mode: e.g. slice0/0x0)")
    ap.add_argument("--advertise", default="127.0.0.1",
                    help="address other nodes reach this node at "
                         "(coordinator rendezvous + log fetch)")
    ap.add_argument("--chips", type=int, default=None,
                    help="chip capacity for scalar-mode node scheduling "
                         "(default: unbounded)")
    ap.add_argument("--logs-dir", default=None)
    ap.add_argument("--ckpt-dir", default=None,
                    help="node-local mount point of the cluster's SHARED "
                         "checkpoint volume (exported to pods as "
                         "TPUJOB_CKPT_DIR; workloads derive "
                         "<dir>/<namespace>/<job> from it so a restarted "
                         "gang re-placed onto other nodes resumes from the "
                         "same path)")
    ap.add_argument("--log-port", type=int, default=0,
                    help="port for the log endpoint (default: ephemeral)")
    ap.add_argument("--heartbeat", type=float, default=2.0)
    ap.add_argument("--eviction-grace", type=float, default=5.0,
                    help="seconds between SIGTERM and SIGKILL for evicted "
                         "pods (≙ terminationGracePeriodSeconds) — the "
                         "window a preempted trainer uses to force-"
                         "checkpoint; 0 = immediate SIGKILL")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--hollow", action="store_true",
                    help="kubemark mode: run the real agent loop (watch, "
                         "bind pickup, heartbeats, patch-batches) but walk "
                         "pods through a SCRIPTED phase timeline instead of "
                         "launching processes — control-plane scale testing "
                         "without the hardware")
    ap.add_argument("--hollow-run-s", type=float, default=0.5,
                    help="--hollow: scripted Running duration per pod")
    ap.add_argument("--hollow-pending-s", type=float, default=0.0,
                    help="--hollow: bind-pickup to Running delay")
    ap.add_argument("--hollow-failure-rate", type=float, default=0.0,
                    help="--hollow: probability a pod terminates Failed "
                         "(seeded; exercises the gang-restart paths)")
    ap.add_argument("--hollow-seed", type=int, default=0)
    ap.add_argument("--tls-ca-file", default=None,
                    help="CA bundle (or the self-signed cert itself) to "
                         "verify a --store https://... against")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    trace.configure_from_env("agent")
    from mpi_operator_tpu.machinery.http_store import read_token_file
    from mpi_operator_tpu.opshell.__main__ import build_store

    if args.store == "memory":
        print("error: --store memory is private to one process; an agent "
              "needs the shared store the scheduler binds into",
              file=sys.stderr)
        return 2
    try:
        token = read_token_file(args.token_file)
        read_token = read_token_file(args.read_token_file)
    except (OSError, ValueError) as e:
        print(f"error: token file: {e}", file=sys.stderr)
        return 2
    if read_token is not None and token is None:
        # same fail-closed posture as tpu-store and tpu-operator: a read
        # tier without the admin tier means an unauthenticated store
        # connection nobody asked for
        print("error: --read-token-file requires --token-file "
              "(the admin tier anchors auth)", file=sys.stderr)
        return 2
    store = build_store(args.store, token=token, ca_file=args.tls_ca_file)
    hollow = None
    if args.hollow:
        from mpi_operator_tpu.executor.hollow import HollowTimeline

        hollow = HollowTimeline(
            pending_s=args.hollow_pending_s,
            run_s=args.hollow_run_s,
            failure_rate=args.hollow_failure_rate,
            seed=args.hollow_seed,
        )
    try:
        agent = NodeAgent(
            store,
            args.node_name,
            advertise=args.advertise,
            capacity_chips=args.chips,
            logs_dir=args.logs_dir,
            log_port=args.log_port,
            workdir=args.workdir,
            heartbeat_interval=args.heartbeat,
            log_tokens=[t for t in (token, read_token) if t],
            ckpt_dir=args.ckpt_dir,
            eviction_grace=args.eviction_grace,
            hollow=hollow,
        ).start()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"node agent {args.node_name} running "
          f"(logs http://{args.advertise}:{agent.log_server.port}/logs)",
          flush=True)
    stop = threading.Event()

    def on_signal(sig, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    agent.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
