"""Pod executors: turn controller-created Pod objects into running work.

The reference has no executor of its own — kubelet plays this role, and the
reference's CI therefore cannot run a single job end-to-end (SURVEY.md §4:
pod phases are *simulated* in envtest). Because this framework's pods are
plain process specs, a real local executor is cheap, and the whole stack —
job YAML → reconcile → gang placement → SPMD boot → collectives → status
mirror — runs end-to-end in-suite with zero cluster.
"""

from mpi_operator_tpu.executor.local import LocalExecutor


def __getattr__(name):
    # NodeAgent lazily: importing it pulls in the agent's HTTP server bits,
    # which pure-LocalExecutor users (worker images) never need
    if name == "NodeAgent":
        from mpi_operator_tpu.executor.agent import NodeAgent

        return NodeAgent
    raise AttributeError(name)


__all__ = ["LocalExecutor", "NodeAgent"]
