"""Quantized matmul path: int8 / fp8 forward, full-precision backward.

ISSUE 16 tentpole (d): the llama FFN matmuls (w_gate/w_up/w_down — ~2/3 of
the model's FLOPs) can run on the MXU's low-precision throughput tiers.
This module is the config-gated seam: dynamic per-row/per-column absmax
quantization of activations and weights, the contraction itself in the
narrow dtype (``lax.dot_general`` with ``preferred_element_type`` so XLA
lowers to the int8/fp8 MXU path on hardware that has one — v5e int8 is
2x the bf16 peak, v6e adds native fp8), and dequantization folded into the
epilogue as a rank-1 outer-product scale.

Training stays stable because only the FORWARD contraction is quantized:
a ``custom_vjp`` routes the backward through plain full-precision matmuls
(the straight-through estimator — quantization noise is treated as
identity under differentiation). That is the standard QAT recipe; it keeps
the loss landscape intact while the forward eats the rounding error.

Honesty note (PERF.md round 16): on backends whose MXU has no narrow-dtype
tier the compiler upcasts and the path measures pure overhead — the config
flag defaults OFF, and the bench reports the flag it ran with.

Scaling granularity: activations per-row (each [.., K] vector gets its own
scale), weights per-column — the finest granularity expressible as a
rank-1 epilogue, so accuracy degrades per-token/per-feature rather than
per-tensor, with zero extra matmuls.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# quantization grids: int8 symmetric [-127, 127] (dropping -128 keeps the
# grid symmetric so absmax scaling is unbiased); fp8 e4m3 saturates at 448
_QMAX = {"int8": 127.0, "fp8": 448.0}

_DIMS = (((1,), (0,)), ((), ()))  # plain [M,K] @ [K,N]


def _scale(a32: jnp.ndarray, axis: int, qmax: float) -> jnp.ndarray:
    """Per-slice absmax → multiply-by-scale dequant factor, floored so an
    all-zero row/column quantizes to zeros instead of dividing by zero."""
    m = jnp.max(jnp.abs(a32), axis=axis, keepdims=True)
    return jnp.maximum(m, 1e-12) / qmax


def _quantize(a32, scale, precision):
    if precision == "int8":
        return jnp.clip(jnp.round(a32 / scale), -127.0, 127.0).astype(jnp.int8)
    return (a32 / scale).astype(jnp.float8_e4m3fn)


def _forward_2d(x: jnp.ndarray, w: jnp.ndarray, precision: str) -> jnp.ndarray:
    qmax = _QMAX[precision]
    x32 = x.astype(jnp.float32)
    w32 = w.astype(jnp.float32)
    sx = _scale(x32, -1, qmax)  # [M, 1] — per activation row
    sw = _scale(w32, 0, qmax)   # [1, N] — per weight column
    xq = _quantize(x32, sx, precision)
    wq = _quantize(w32, sw, precision)
    acc = lax.dot_general(
        xq, wq, _DIMS,
        preferred_element_type=(
            jnp.int32 if precision == "int8" else jnp.float32
        ),
    )
    return (acc.astype(jnp.float32) * sx * sw).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _quant_mm_2d(x, w, precision):
    return _forward_2d(x, w, precision)


def _quant_mm_fwd(x, w, precision):
    return _forward_2d(x, w, precision), (x, w)


def _quant_mm_bwd(precision, res, g):
    # straight-through: backward ignores the quantizer and differentiates
    # the underlying x @ w in full precision — gradient quality is what
    # keeps QAT training curves tracking the bf16 baseline
    x, w = res
    dx = (g @ w.T.astype(g.dtype)).astype(x.dtype)
    dw = (x.T.astype(g.dtype) @ g).astype(w.dtype)
    return dx, dw


_quant_mm_2d.defvjp(_quant_mm_fwd, _quant_mm_bwd)


def quant_matmul(
    x: jnp.ndarray, w: jnp.ndarray, *, precision: str = "int8"
) -> jnp.ndarray:
    """``x @ w`` with the contraction quantized to ``precision``.

    ``x``: [..., K] (leading dims flattened for the 2D kernel and restored
    after); ``w``: [K, N]. ``precision`` ∈ {"int8", "fp8", "bf16"} — "bf16"
    is the identity escape hatch so call sites can pass the config flag
    straight through."""
    if precision == "bf16":
        return x @ w
    if precision not in _QMAX:
        raise ValueError(
            f"precision={precision!r}; expected int8|fp8|bf16"
        )
    lead = x.shape[:-1]
    out = _quant_mm_2d(x.reshape(-1, x.shape[-1]), w, precision)
    return out.reshape(*lead, w.shape[-1])


def quant_error(x, w, *, precision: str = "int8") -> float:
    """Relative Frobenius error of the quantized product vs the f32 oracle
    — the number PERF.md quotes next to any MFU claim for this path."""
    exact = x.astype(jnp.float32) @ w.astype(jnp.float32)
    approx = quant_matmul(x, w, precision=precision).astype(jnp.float32)
    return float(
        jnp.linalg.norm(approx - exact) / jnp.maximum(jnp.linalg.norm(exact), 1e-12)
    )
