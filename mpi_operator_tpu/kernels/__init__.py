"""Pallas TPU kernels for the framework's hot ops.

The reference has no kernels of its own — its hot path is Horovod/NCCL plus
whatever cuDNN the workload images carry. Here the XLA-compiled model is
already fast; these kernels target the ops where hand scheduling beats the
compiler: attention (VMEM-resident online softmax, no [T,T] materialization).
Written per /opt/skills/guides/pallas_guide.md; every kernel has an
interpret-mode path so the CPU test suite checks numerics.
"""

from mpi_operator_tpu.kernels.flash_attention import flash_attention
from mpi_operator_tpu.kernels.quant_matmul import quant_matmul

__all__ = ["flash_attention", "quant_matmul"]
