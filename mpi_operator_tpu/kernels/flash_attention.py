"""Flash attention (forward + backward) as Pallas TPU kernels.

Blockwise online-softmax attention: every kernel streams fixed-size Q and
K/V tiles through a 4-D grid, so VMEM use is O(block·head_dim) regardless
of sequence length — the [T, T] score matrix never exists, no full-sequence
array is ever VMEM-resident (the first kernel generation held whole K/V per
program and died at T≈16k against the 16 MB scoped-VMEM limit), and
T is bounded only by HBM. GQA-aware: the kv head for a q head is derived in
the BlockSpec index maps (no K/V expansion in HBM).

Layout: [B, H, T, D] (heads-major — the kernel-friendly transpose of the
model's [B, T, H, D]; the wrapper handles it). bf16 operands on the MXU,
f32 accumulation in VMEM scratch that persists across the innermost grid
dimension; outputs are written on that dimension's final step.

Backward is FlashAttention-2-style: the forward additionally emits the
log-sum-exp rows, and the backward recomputes probabilities blockwise
on-chip to produce dq (grid over q tiles × streamed K/V) and dk/dv (grid
over k tiles × streamed Q) — neither direction round-trips a score block
through HBM. Profiling the Llama train step showed the previous
recompute-through-XLA backward was the single largest cost: ~330 ms/step
of HBM-bound score-block traffic on v5e.

Pallas custom calls have no SPMD partitioning rule, so on a sharded mesh the
kernel must run under shard_map; pass ``mesh`` and the wrapper shards batch
over (data, fsdp) and heads over tensor, running the kernel on local shards.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from mpi_operator_tpu.jaxcompat import shard_map
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pad_t(x, t_pad: int):
    """Zero-pad dim 2 (sequence) of [B,H,T,D]-like arrays up to t_pad."""
    t = x.shape[2]
    if t == t_pad:
        return x
    return jnp.pad(x, [(0, 0), (0, 0), (0, t_pad - t)] + [(0, 0)] * (x.ndim - 3))


# Causal tile-skip algebra, shared by the kernels' pl.when predicates and
# the BlockSpec index-map clamps (a clamped index repeats on skipped grid
# steps, so pallas elides the dead tiles' DMAs). The two sides MUST agree:
# a tile is computed iff ki * bk < (qi + 1) * bq ("diag open").


def _causal_open(qi, ki, bq: int, bk: int):
    """True iff k tile ki intersects the causal (lower-triangular) region
    of q tile qi — the kernels' compute-skip predicate."""
    return ki * bk < (qi + 1) * bq


def _causal_last_k_tile(qi, bq: int, bk: int):
    """Largest ki with _causal_open(qi, ki): ceil((qi+1)*bq / bk) - 1."""
    return ((qi + 1) * bq + bk - 1) // bk - 1


def _causal_first_q_tile(ki, bq: int, bk: int):
    """Smallest qi with _causal_open(qi, ki): (ki*bk) // bq."""
    return (ki * bk) // bq


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
    block_q: int, block_k: int, n_kb: int, causal: bool, scale: float,
    t_real: int,
):
    """One grid step folds one (q-tile, k-tile) pair. Grid (b, h, qi, ki),
    ki innermost: the f32 scratch (acc, m, l) carries the online softmax
    across a q-tile's k sweep; o/lse are written on the sweep's last step.
    Refs: q/o [1,1,BQ,D], k/v [1,1,BK,D], lse [1,1,BQ,1]."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: tiles strictly above the diagonal contribute nothing; skip
    # their compute (the matching index-map clamp elides their DMAs too)
    diag_open = _causal_open(qi, ki, block_q, block_k) if causal else True

    @pl.when(diag_open)
    def _fold():
        q = q_ref[0, 0]  # input dtype: full-rate MXU, f32 accumulate
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] f32
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_idx < t_real  # edge tiles read past t: mask them
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0] = l_prev * corr + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_kb - 1)
    def _emit():
        l = l_ref[:, 0]
        # fully-masked rows (q padding) have l == 0; emit 0, not NaN
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[...] + jnp.log(safe[:, None]))


def _flash_fwd(
    q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int,
    interpret: bool,
):
    """q [B,H,T,D], k/v [B,Hkv,T,D] → (o [B,H,T,D], lse [B,H,Tq_pad,1])."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    bq = min(block_q, t)
    bk = min(block_k, t)
    n_qb = pl.cdiv(t, bq)
    n_kb = pl.cdiv(t, bk)
    grid = (b, h, n_qb, n_kb)

    # zero-pad to block multiples: an edge tile's OOB region is otherwise
    # undefined memory, and 0·NaN = NaN leaks through masked weights in the
    # PV product (zero weights do NOT neutralize NaN operands). Padding is
    # a no-op at production sizes; the score mask (t_real) keeps padded
    # keys from attending.
    q = _pad_t(q, n_qb * bq)
    k = _pad_t(k, n_kb * bk)
    v = _pad_t(v, n_kb * bk)

    kernel = functools.partial(
        _fwd_kernel, block_q=bq, block_k=bk, n_kb=n_kb, causal=causal,
        scale=scale, t_real=t,
    )

    # causal: a k tile strictly above the diagonal is skipped by the kernel
    # (pl.when) — clamping its block index to the last USED tile makes the
    # index map repeat, so pallas elides the DMA too. ~2x less K/V traffic
    # at long T (the causally-dead half of the rectangle grid).
    def kv_index(bi, hi, qi, ki):
        if causal:
            ki = jnp.minimum(ki, _causal_last_k_tile(qi, bq, bk))
        return (bi, hi // g, ki, 0)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d), kv_index),
            pl.BlockSpec((1, 1, bk, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_qb * bq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, n_qb * bq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o[:, :, :t], lse


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref, *,
    block_q: int, block_k: int, n_kb: int, causal: bool, scale: float,
    t_real: int,
):
    """dq: grid (b, h, qi, ki) streams K/V tiles past each q tile,
    recomputing P on-chip from the saved LSE. Refs: q/do/dq [1,1,BQ,D],
    k/v [1,1,BK,D], lse/delta [1,1,BQ,1]."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    diag_open = _causal_open(qi, ki, block_q, block_k) if causal else True

    @pl.when(diag_open)
    def _fold():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_idx < t_real
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        # p rows are already normalized: lse folds in the denominator
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_kb - 1)
    def _emit():
        dq_ref[0, 0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref, *,
    block_q: int, block_k: int, n_qb: int, causal: bool, scale: float,
    t_real: int,
):
    """dk/dv: grid (b, h, ki, qi) streams Q/dO tiles past each k tile. GQA:
    outputs are per *q* head; the wrapper group-sums to kv heads. Refs:
    k/v/dk/dv [1,1,BK,D], q/do [1,1,BQ,D], lse/delta [1,1,BQ,1]."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    diag_open = _causal_open(qi, ki, block_q, block_k) if causal else True

    @pl.when(diag_open)
    def _fold():
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = jnp.logical_and(q_idx < t_real, k_idx < t_real)
        if causal:
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == n_qb - 1)
    def _emit():
        dk_ref[0, 0] = (dk_acc_ref[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(
    q, k, v, o, lse, do, *, causal: bool, scale: float, block_q: int,
    block_k: int, interpret: bool,
):
    """Pallas flash backward. q/o/do [B,H,T,D], k/v [B,Hkv,T,D],
    lse [B,H,Tq_pad,1] → (dq, dk, dv) in input shapes/dtypes."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    bq = min(block_q, t)
    bk = min(block_k, t)
    n_qb = pl.cdiv(t, bq)
    n_kb = pl.cdiv(t, bk)

    # delta_i = dO_i · O_i — the rowwise residual term of d(softmax);
    # trailing singleton matches the lse layout. Everything zero-padded to
    # block multiples (see _flash_fwd: undefined OOB tile memory leaks NaN
    # through masked products).
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, T, 1]
    delta = _pad_t(delta, n_qb * bq)
    q_p = _pad_t(q, n_qb * bq)
    do_p = _pad_t(do, n_qb * bq)
    k_p = _pad_t(k, n_kb * bk)
    v_p = _pad_t(v, n_kb * bk)

    # causally-skipped tiles: clamp the index map so the DMA is elided too
    # (see the same trick in _flash_fwd)
    def kv_index(bi, hi, qi, ki):
        if causal:
            ki = jnp.minimum(ki, _causal_last_k_tile(qi, bq, bk))
        return (bi, hi // g, ki, 0)

    def q_index_dkv(bi, hi, ki, qi):
        if causal:
            qi = jnp.maximum(qi, _causal_first_q_tile(ki, bq, bk))
        return (bi, hi, qi, 0)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=bq, block_k=bk, n_kb=n_kb,
            causal=causal, scale=scale, t_real=t,
        ),
        grid=(b, h, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bk, d), kv_index),
            pl.BlockSpec((1, 1, bk, d), kv_index),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, n_qb * bq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse, delta)[:, :, :t]

    # dk/dv per q-head (grid over k tiles, q innermost); kv grads group-sum
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, block_k=bk, n_qb=n_qb,
            causal=causal, scale=scale, t_real=t,
        ),
        grid=(b, h, n_kb, n_qb),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_index_dkv),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, bq, d), q_index_dkv),
            pl.BlockSpec((1, 1, bq, 1), q_index_dkv),
            pl.BlockSpec((1, 1, bq, 1), q_index_dkv),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        # partials in the input dtype (f32 accumulation stays in scratch):
        # the per-q-head [B,H,T,D] pair is the backward's largest transient,
        # and the group-sum result is cast to k.dtype regardless
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_kb * bk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, n_kb * bk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse, delta)

    dk = (
        dk_h[:, :, :t]
        .reshape(b, h_kv, g, t, d)
        .astype(jnp.float32)
        .sum(axis=2)
        .astype(k.dtype)
    )
    dv = (
        dv_h[:, :, :t]
        .reshape(b, h_kv, g, t, d)
        .astype(jnp.float32)
        .sum(axis=2)
        .astype(v.dtype)
    )
    return dq, dk, dv


def _block_reference(q_blk, k, v, q_offset, *, causal: bool, scale: float):
    """Attention for one q block against full K/V (heads-major, GQA-aware).
    q_blk [B,H,BQ,D], k/v [B,Hkv,T,D], q_offset scalar start index."""
    b, h, bq, d = q_blk.shape
    h_kv = k.shape[1]
    g = h // h_kv
    q5 = q_blk.reshape(b, h_kv, g, bq, d).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", q5, k.astype(jnp.float32)) * scale
    s = s.reshape(b, h, bq, k.shape[2])
    if causal:
        q_idx = q_offset + jnp.arange(bq)[:, None]
        k_idx = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((q_idx >= k_idx)[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p5 = p.reshape(b, h_kv, g, bq, k.shape[2])
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p5, v.astype(p.dtype))
    return o.reshape(b, h, bq, d).astype(q_blk.dtype)


def _chunked_reference(q, k, v, *, causal: bool, scale: float, block_q: int):
    """Memory-bounded XLA attention: lax.map over checkpointed q blocks, so
    its vjp stores only block inputs and recomputes scores blockwise —
    backward memory stays O(BQ·T) instead of [T,T]. The non-TPU fallback
    and the independent lowering the on-chip checks compare against."""
    b, h, t, d = q.shape
    bq = min(block_q, t)
    n = -(-t // bq)
    t_pad = n * bq
    q_p = _pad_t(q, t_pad)
    qr = q_p.reshape(b, h, n, bq, d).transpose(2, 0, 1, 3, 4)  # [n,B,H,BQ,D]
    offsets = jnp.arange(n) * bq

    blk = jax.checkpoint(
        lambda qb, off: _block_reference(qb, k, v, off, causal=causal, scale=scale)
    )
    out = jax.lax.map(lambda args: blk(*args), (qr, offsets))  # [n,B,H,BQ,D]
    out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, t_pad, d)
    return out[:, :, :t]


def _dense_reference(q, k, v, *, causal: bool, scale: float):
    """Unchunked XLA reference (numerics tests)."""
    return _block_reference(q, k, v, 0, causal=causal, scale=scale)


def chunked_reference(q, k, v, *, causal: bool = True, scale=None, block_q: int = 256):
    """The chunked XLA reference in *model* layout (q [B,T,H,D]) — the
    independent lowering that on-hardware checks (bench.py's pre-timing
    gate, tests_tpu/) compare the compiled kernel against."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _chunked_reference(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        scale=scale,
        block_q=block_q,
    ).transpose(0, 2, 1, 3)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    return o


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    # named so a rematted caller can elect to SAVE these residuals (o is
    # cheap to keep, recomputing it costs a full kernel pass) — see
    # models.llama.apply's save_only_these_names policy
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(
        q, k, v, o, lse, do, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    mesh=None,
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
    layout: str = "bthd",
):
    """Flash attention in model layout q [B,T,H,D], k/v [B,T,Hkv,D] — or,
    with ``layout="bhtd"``, directly in the kernel's heads-major layout
    (a caller that PRODUCES q/k/v heads-major skips the [B,T,H,D]↔[B,H,T,D]
    copies the wrapper otherwise pays on every call, ~3% of the llama step).

    With ``mesh``, runs under shard_map (batch over ``batch_axes``, heads
    over ``head_axis`` when divisible) — required for sharded inputs, since
    the pallas call is not SPMD-partitionable. ``interpret=None`` (auto)
    runs the real kernel on TPU and the exact chunked XLA reference on any
    other backend — never the Pallas interpreter; pass ``interpret=True``
    explicitly to exercise the kernel body off-TPU (kernel tests do).
    Differentiable (Pallas flash backward)."""
    if layout not in ("bthd", "bhtd"):
        raise ValueError(f"layout={layout!r}; expected bthd|bhtd")
    heads_major = layout == "bhtd"
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # interpret=None means "auto": the real kernel on TPU; elsewhere the
    # chunked XLA reference (same math, same memory bound) — NOT interpret
    # mode, which is orders of magnitude slower than XLA and only useful
    # when a test explicitly asks to exercise the kernel body.
    use_kernel = interpret is not None or jax.default_backend() == "tpu"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(q_, k_, v_):
        if heads_major:
            qt, kt, vt = q_, k_, v_
        else:
            qt = q_.transpose(0, 2, 1, 3)
            kt = k_.transpose(0, 2, 1, 3)
            vt = v_.transpose(0, 2, 1, 3)
        if use_kernel:
            o = _flash(qt, kt, vt, causal, scale, block_q, block_k, interpret)
        else:
            o = _chunked_reference(
                qt, kt, vt, causal=causal, scale=scale, block_q=block_q
            )
        return o if heads_major else o.transpose(0, 2, 1, 3)

    if mesh is None:
        return local(q, k, v)

    from jax.sharding import PartitionSpec as P

    b_part = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    h_dim = 1 if heads_major else 2
    h, h_kv = q.shape[h_dim], k.shape[h_dim]
    tp = mesh.shape.get(head_axis, 1) if head_axis in mesh.axis_names else 1
    # heads shard only when BOTH head counts divide: the GQA grouping must
    # stay aligned on every shard
    h_part = head_axis if (tp > 1 and h % tp == 0 and h_kv % tp == 0) else None
    spec = (
        P(b_part, h_part, None, None)
        if heads_major
        else P(b_part, None, h_part, None)
    )
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, so shard_map's vma checker rejects it; the specs above are
    # the full partitioning contract anyway.
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
