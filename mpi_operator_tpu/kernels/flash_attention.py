"""Flash attention (forward) as a Pallas TPU kernel.

Blockwise online-softmax attention: Q tiles stream against K/V tiles held in
VMEM, the [T, T] score matrix never exists, and each (batch, head, q-tile)
program owns one output tile. GQA-aware: the kv head for a q head is derived
in the BlockSpec index maps (no K/V expansion in HBM).

Layout: [B, H, T, D] (heads-major — the kernel-friendly transpose of the
model's [B, T, H, D]; the wrapper handles it). bf16 in, f32 accumulate, bf16
out — MXU-native.

Backward is a pair of Pallas kernels (FlashAttention-2 style): the forward
additionally emits the log-sum-exp rows, and the backward recomputes
probabilities blockwise on-chip to produce dq (grid over q tiles) and
dk/dv (grid over k tiles) — neither direction ever materializes [T,T] nor
round-trips a score block through HBM. Profiling the Llama train step
showed the previous recompute-through-XLA backward was the single largest
cost: ~330 ms/step of HBM-bound score-block traffic on v5e.

Pallas custom calls have no SPMD partitioning rule, so on a sharded mesh the
kernel must run under shard_map; pass ``mesh`` and the wrapper shards batch
over (data, fsdp) and heads over tensor, running the kernel on local shards.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
    causal: bool, scale: float, t_real: int
):
    """One program = one (b, h, q-tile). Refs:
    q [1,1,BQ,D], k/v [1,1,Tpad,D], o [1,1,BQ,D], lse [1,1,BQ]. K/V are
    pre-padded to a block_k multiple (pl.ds clamps OOB starts, so unpadded
    tail tiles would silently re-read earlier rows); t_real masks the pad."""
    qb = pl.program_id(2)
    # dots run in the input dtype (bf16 in production = full MXU rate; the
    # f32 cast would halve it) with f32 accumulation; scale folds into the
    # f32 scores
    q = q_ref[0, 0]  # [BQ, D]
    bq, d = q.shape
    t = t_real
    n_kb = pl.cdiv(t, block_k)

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK] f32
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1
        )
        # tail K tiles are padded past t — padded keys must not attend
        valid = k_idx < t
        if causal:
            q_idx = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    if causal:
        # skip key tiles strictly above the diagonal for this q tile
        n_kb = jnp.minimum(n_kb, pl.cdiv((qb + 1) * bq, block_k))
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kb, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    # log-sum-exp rows: the backward's sole softmax residual. Trailing
    # singleton lane dim keeps the block shape TPU-lowerable ((bq, 1) —
    # mosaic wants last-two dims (8k, 128k) or equal to the array's).
    lse_ref[0, 0] = (m + jnp.log(l))[:, None]


def _flash_fwd(
    q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int,
    interpret: bool,
):
    """q [B,H,T,D], k/v [B,Hkv,T,D] → (o [B,H,T,D], lse [B,H,Tq_pad,1])."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    bq = min(block_q, t)
    bk = min(block_k, t)
    n_qb = pl.cdiv(t, bq)
    grid = (b, h, n_qb)

    # pad K/V up to a block multiple: pl.ds clamps OOB starts, so a partial
    # tail tile would otherwise alias earlier rows
    t_pad = ((t + bk - 1) // bk) * bk
    if t_pad != t:
        pad = [(0, 0), (0, 0), (0, t_pad - t), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    kernel = functools.partial(
        _fwd_kernel, block_k=bk, causal=causal, scale=scale, t_real=t
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, t_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, t_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, n_qb * bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
    block_k: int, causal: bool, scale: float, t_real: int,
):
    """dq for one (b, h, q-tile): stream K/V tiles, recompute P on-chip.
    Refs: q/do/dq [1,1,BQ,D], k/v [1,1,Tpad,D], lse/delta [1,1,BQ,1]."""
    qb = pl.program_id(2)
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0, :, 0]
    delta = delta_ref[0, 0, :, 0]
    bq, d = q.shape
    n_kb = pl.cdiv(t_real, block_k)
    if causal:
        n_kb = jnp.minimum(n_kb, pl.cdiv((qb + 1) * bq, block_k))

    def body(kb, acc):
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1
        )
        valid = k_idx < t_real
        if causal:
            q_idx = qb * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        # p rows are already normalized: lse folds in the softmax denominator
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        return acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    acc = jax.lax.fori_loop(0, n_kb, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = (acc * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
    block_q: int, causal: bool, scale: float, t_real: int,
):
    """dk/dv for one (b, h, k-tile): stream Q/dO tiles, recompute P^T
    on-chip. GQA: outputs are per *q* head; the wrapper group-sums to kv
    heads. Refs: k/v/dk/dv [1,1,BK,D], q/do [1,1,Tqpad,D],
    lse/delta [1,1,Tqpad,1]."""
    kb = pl.program_id(2)
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    bk, d = k.shape
    t_q = q_ref.shape[2]
    n_qb = t_q // block_q
    qb0 = (kb * bk) // block_q if causal else 0

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        do = do_ref[0, 0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q), 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [BQ, BK]
        q_idx = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0
        )
        k_idx = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        valid = jnp.logical_and(q_idx < t_real, k_idx < t_real)
        if causal:
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_acc, dv_acc

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb0, n_qb, body, (z, z))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(
    q, k, v, o, lse, do, *, causal: bool, scale: float, block_q: int,
    block_k: int, interpret: bool,
):
    """Pallas flash backward. q/o/do [B,H,T,D], k/v [B,Hkv,T,D],
    lse [B,H,Tq_pad,1] → (dq, dk, dv) in input shapes/dtypes."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    bq = min(block_q, t)
    bk = min(block_k, t)
    n_qb = pl.cdiv(t, bq)
    tq_pad = n_qb * bq
    tk_pad = pl.cdiv(t, bk) * bk

    # delta_i = dO_i · O_i — the rowwise residual term of d(softmax);
    # trailing singleton matches the lse layout
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, T, 1]
    if tq_pad != t:
        pad4 = [(0, 0), (0, 0), (0, tq_pad - t), (0, 0)]
        delta = jnp.pad(delta, pad4)
        q_p = jnp.pad(q, pad4)
        do_p = jnp.pad(do, pad4)
    else:
        q_p, do_p = q, do
    if tk_pad != t:
        pad4 = [(0, 0), (0, 0), (0, tk_pad - t), (0, 0)]
        k_p = jnp.pad(k, pad4)
        v_p = jnp.pad(v, pad4)
    else:
        k_p, v_p = k, v

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=bk, causal=causal, scale=scale, t_real=t
        ),
        grid=(b, h, n_qb),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, tk_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
    )(q, k_p, v_p, do, lse, delta)

    # dk/dv per q-head (grid over k tiles); kv grads group-sum below
    dk_h, dv_h = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, causal=causal, scale=scale, t_real=t
        ),
        grid=(b, h, tk_pad // bk),
        in_specs=[
            pl.BlockSpec((1, 1, tq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi // g, ki, 0)),
            pl.BlockSpec((1, 1, tq_pad, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, tq_pad, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, tq_pad, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tk_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, tk_pad, d), jnp.float32),
        ],
        interpret=interpret,
    )(q_p, k_p, v_p, do_p, lse, delta)

    dk = dk_h[:, :, :t].reshape(b, h_kv, g, t, d).sum(axis=2).astype(k.dtype)
    dv = dv_h[:, :, :t].reshape(b, h_kv, g, t, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


def _block_reference(q_blk, k, v, q_offset, *, causal: bool, scale: float):
    """Attention for one q block against full K/V (heads-major, GQA-aware).
    q_blk [B,H,BQ,D], k/v [B,Hkv,T,D], q_offset scalar start index."""
    b, h, bq, d = q_blk.shape
    h_kv = k.shape[1]
    g = h // h_kv
    q5 = q_blk.reshape(b, h_kv, g, bq, d).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", q5, k.astype(jnp.float32)) * scale
    s = s.reshape(b, h, bq, k.shape[2])
    if causal:
        q_idx = q_offset + jnp.arange(bq)[:, None]
        k_idx = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((q_idx >= k_idx)[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p5 = p.reshape(b, h_kv, g, bq, k.shape[2])
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p5, v.astype(p.dtype))
    return o.reshape(b, h, bq, d).astype(q_blk.dtype)


def _chunked_reference(q, k, v, *, causal: bool, scale: float, block_q: int):
    """Memory-bounded XLA attention: lax.map over checkpointed q blocks, so
    its vjp stores only block inputs and recomputes scores blockwise —
    backward memory stays O(BQ·T) instead of [T,T]. This is the function the
    flash kernel's custom_vjp differentiates."""
    b, h, t, d = q.shape
    bq = min(block_q, t)
    n = -(-t // bq)
    t_pad = n * bq
    q_p = jnp.pad(q, [(0, 0), (0, 0), (0, t_pad - t), (0, 0)]) if t_pad != t else q
    qr = q_p.reshape(b, h, n, bq, d).transpose(2, 0, 1, 3, 4)  # [n,B,H,BQ,D]
    offsets = jnp.arange(n) * bq

    blk = jax.checkpoint(
        lambda qb, off: _block_reference(qb, k, v, off, causal=causal, scale=scale)
    )
    out = jax.lax.map(lambda args: blk(*args), (qr, offsets))  # [n,B,H,BQ,D]
    out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, t_pad, d)
    return out[:, :, :t]


def _dense_reference(q, k, v, *, causal: bool, scale: float):
    """Unchunked XLA reference (numerics tests)."""
    return _block_reference(q, k, v, 0, causal=causal, scale=scale)


def chunked_reference(q, k, v, *, causal: bool = True, scale=None, block_q: int = 256):
    """The chunked XLA reference in *model* layout (q [B,T,H,D]) — the
    independent lowering that on-hardware checks (bench.py's pre-timing
    gate, tests_tpu/) compare the compiled kernel against."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _chunked_reference(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        scale=scale,
        block_q=block_q,
    ).transpose(0, 2, 1, 3)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    return o


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    # named so a rematted caller can elect to SAVE these residuals (o is
    # cheap to keep, recomputing it costs a full kernel pass) — see
    # models.llama.apply's save_only_these_names policy
    from jax.ad_checkpoint import checkpoint_name

    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(
        q, k, v, o, lse, do, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    mesh=None,
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
):
    """Flash attention in model layout q [B,T,H,D], k/v [B,T,Hkv,D].

    With ``mesh``, runs under shard_map (batch over ``batch_axes``, heads
    over ``head_axis`` when divisible) — required for sharded inputs, since
    the pallas call is not SPMD-partitionable. ``interpret=None`` (auto)
    runs the real kernel on TPU and the exact chunked XLA reference on any
    other backend — never the Pallas interpreter; pass ``interpret=True``
    explicitly to exercise the kernel body off-TPU (kernel tests do).
    Differentiable (blockwise recompute backward)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # interpret=None means "auto": the real kernel on TPU; elsewhere the
    # chunked XLA reference (same math, same memory bound) — NOT interpret
    # mode, which is orders of magnitude slower than XLA and only useful
    # when a test explicitly asks to exercise the kernel body.
    use_kernel = interpret is not None or jax.default_backend() == "tpu"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(q_, k_, v_):
        qt = q_.transpose(0, 2, 1, 3)
        kt = k_.transpose(0, 2, 1, 3)
        vt = v_.transpose(0, 2, 1, 3)
        if use_kernel:
            o = _flash(qt, kt, vt, causal, scale, block_q, block_k, interpret)
        else:
            o = _chunked_reference(
                qt, kt, vt, causal=causal, scale=scale, block_q=block_q
            )
        return o.transpose(0, 2, 1, 3)

    if mesh is None:
        return local(q, k, v)

    from jax.sharding import PartitionSpec as P

    b_part = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    h, h_kv = q.shape[2], k.shape[2]
    tp = mesh.shape.get(head_axis, 1) if head_axis in mesh.axis_names else 1
    # heads shard only when BOTH head counts divide: the GQA grouping must
    # stay aligned on every shard
    h_part = head_axis if (tp > 1 and h % tp == 0 and h_kv % tp == 0) else None
    spec = P(b_part, None, h_part, None)
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, so shard_map's vma checker rejects it; the specs above are
    # the full partitioning contract anyway.
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
