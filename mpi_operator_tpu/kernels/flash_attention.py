"""Flash attention (forward) as a Pallas TPU kernel.

Blockwise online-softmax attention: Q tiles stream against K/V tiles held in
VMEM, the [T, T] score matrix never exists, and each (batch, head, q-tile)
program owns one output tile. GQA-aware: the kv head for a q head is derived
in the BlockSpec index maps (no K/V expansion in HBM).

Layout: [B, H, T, D] (heads-major — the kernel-friendly transpose of the
model's [B, T, H, D]; the wrapper handles it). bf16 in, f32 accumulate, bf16
out — MXU-native.

Backward uses recompute-through-XLA via custom_vjp: the forward saves only
(q, k, v) and the backward re-derives the attention blockwise (checkpointed
q blocks under lax.map) — neither direction ever materializes [T,T].

Pallas custom calls have no SPMD partitioning rule, so on a sharded mesh the
kernel must run under shard_map; pass ``mesh`` and the wrapper shards batch
over (data, fsdp) and heads over tensor, running the kernel on local shards.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k: int,
    causal: bool, scale: float, t_real: int
):
    """One program = one (b, h, q-tile). Refs:
    q [1,1,BQ,D], k/v [1,1,Tpad,D], o [1,1,BQ,D], m/l [1,1,BQ]. K/V are
    pre-padded to a block_k multiple (pl.ds clamps OOB starts, so unpadded
    tail tiles would silently re-read earlier rows); t_real masks the pad."""
    qb = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [BQ, D]
    bq, d = q.shape
    t = t_real
    n_kb = pl.cdiv(t, block_k)

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        k_idx = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1
        )
        # tail K tiles are padded past t — padded keys must not attend
        valid = k_idx < t
        if causal:
            q_idx = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            valid = jnp.logical_and(valid, q_idx >= k_idx)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    if causal:
        # skip key tiles strictly above the diagonal for this q tile
        n_kb = jnp.minimum(n_kb, pl.cdiv((qb + 1) * bq, block_k))
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kb, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(
    q, k, v, *, causal: bool, scale: float, block_q: int, block_k: int,
    interpret: bool,
):
    """q [B,H,T,D], k/v [B,Hkv,T,D] → (o [B,H,T,D], m,l [B,H,T])."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    bq = min(block_q, t)
    bk = min(block_k, t)
    grid = (b, h, pl.cdiv(t, bq))

    # pad K/V up to a block multiple: pl.ds clamps OOB starts, so a partial
    # tail tile would otherwise alias earlier rows
    t_pad = ((t + bk - 1) // bk) * bk
    if t_pad != t:
        pad = [(0, 0), (0, 0), (0, t_pad - t), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    kernel = functools.partial(
        _fwd_kernel, block_k=bk, causal=causal, scale=scale, t_real=t
    )
    o = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, t_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
            pl.BlockSpec((1, 1, t_pad, d), lambda bi, hi, qi: (bi, hi // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
    )(q, k, v)
    return o


def _block_reference(q_blk, k, v, q_offset, *, causal: bool, scale: float):
    """Attention for one q block against full K/V (heads-major, GQA-aware).
    q_blk [B,H,BQ,D], k/v [B,Hkv,T,D], q_offset scalar start index."""
    b, h, bq, d = q_blk.shape
    h_kv = k.shape[1]
    g = h // h_kv
    q5 = q_blk.reshape(b, h_kv, g, bq, d).astype(jnp.float32)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", q5, k.astype(jnp.float32)) * scale
    s = s.reshape(b, h, bq, k.shape[2])
    if causal:
        q_idx = q_offset + jnp.arange(bq)[:, None]
        k_idx = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((q_idx >= k_idx)[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p5 = p.reshape(b, h_kv, g, bq, k.shape[2])
    o = jnp.einsum("bhgqk,bhkd->bhgqd", p5, v.astype(p.dtype))
    return o.reshape(b, h, bq, d).astype(q_blk.dtype)


def _chunked_reference(q, k, v, *, causal: bool, scale: float, block_q: int):
    """Memory-bounded XLA attention: lax.map over checkpointed q blocks, so
    its vjp stores only block inputs and recomputes scores blockwise —
    backward memory stays O(BQ·T) instead of [T,T]. This is the function the
    flash kernel's custom_vjp differentiates."""
    b, h, t, d = q.shape
    bq = min(block_q, t)
    n = -(-t // bq)
    t_pad = n * bq
    q_p = jnp.pad(q, [(0, 0), (0, 0), (0, t_pad - t), (0, 0)]) if t_pad != t else q
    qr = q_p.reshape(b, h, n, bq, d).transpose(2, 0, 1, 3, 4)  # [n,B,H,BQ,D]
    offsets = jnp.arange(n) * bq

    blk = jax.checkpoint(
        lambda qb, off: _block_reference(qb, k, v, off, causal=causal, scale=scale)
    )
    out = jax.lax.map(lambda args: blk(*args), (qr, offsets))  # [n,B,H,BQ,D]
    out = out.transpose(1, 2, 0, 3, 4).reshape(b, h, t_pad, d)
    return out[:, :, :t]


def _dense_reference(q, k, v, *, causal: bool, scale: float):
    """Unchunked XLA reference (numerics tests)."""
    return _block_reference(q, k, v, 0, causal=causal, scale=scale)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o = _flash_fwd(
        q, k, v, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, interpret=interpret,
    )
    return o, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, do):
    # Recompute-through-XLA backward over checkpointed q blocks: exact
    # gradients, O(BQ·T) live memory, never a [T,T] residual.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_reference(
            q_, k_, v_, causal=causal, scale=scale, block_q=block_q
        ),
        q, k, v,
    )
    return vjp(do)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: Optional[bool] = None,
    mesh=None,
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
):
    """Flash attention in model layout q [B,T,H,D], k/v [B,T,Hkv,D].

    With ``mesh``, runs under shard_map (batch over ``batch_axes``, heads
    over ``head_axis`` when divisible) — required for sharded inputs, since
    the pallas call is not SPMD-partitionable. ``interpret=None`` (auto)
    runs the real kernel on TPU and the exact chunked XLA reference on any
    other backend — never the Pallas interpreter; pass ``interpret=True``
    explicitly to exercise the kernel body off-TPU (kernel tests do).
    Differentiable (blockwise recompute backward)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # interpret=None means "auto": the real kernel on TPU; elsewhere the
    # chunked XLA reference (same math, same memory bound) — NOT interpret
    # mode, which is orders of magnitude slower than XLA and only useful
    # when a test explicitly asks to exercise the kernel body.
    use_kernel = interpret is not None or jax.default_backend() == "tpu"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def local(q_, k_, v_):
        qt = q_.transpose(0, 2, 1, 3)
        kt = k_.transpose(0, 2, 1, 3)
        vt = v_.transpose(0, 2, 1, 3)
        if use_kernel:
            o = _flash(qt, kt, vt, causal, scale, block_q, block_k, interpret)
        else:
            o = _chunked_reference(
                qt, kt, vt, causal=causal, scale=scale, block_q=block_q
            )
        return o.transpose(0, 2, 1, 3)

    if mesh is None:
        return local(q, k, v)

    from jax.sharding import PartitionSpec as P

    b_part = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    h, h_kv = q.shape[2], k.shape[2]
    tp = mesh.shape.get(head_axis, 1) if head_axis in mesh.axis_names else 1
    # heads shard only when BOTH head counts divide: the GQA grouping must
    # stay aligned on every shard
    h_part = head_axis if (tp > 1 and h % tp == 0 and h_kv % tp == 0) else None
    spec = P(b_part, None, h_part, None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
