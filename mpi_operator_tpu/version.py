"""Build/version stamp.

≙ /root/reference/pkg/version/version.go:21-45 (+ the ldflags wiring in
Makefile:9-16): Version/GitSHA/Built surfaced through a --version flag and
importable constants. The ldflags equivalent here is the environment at
image-build time (Dockerfile can bake TPUJOB_BUILD_* in); at runtime the
git SHA falls back to the working tree when available.
"""

from __future__ import annotations

import os
import subprocess

VERSION = "2.0.0"


def git_sha() -> str:
    baked = os.environ.get("TPUJOB_BUILD_SHA")
    if baked:
        return baked
    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True,
                text=True,
                timeout=5,
            ).stdout.strip()
            or "unknown"
        )
    # oplint: disable=EXC001 — version probe (no git, no repo, sandboxed
    # subprocess): "unknown" IS the surfacing; it must never fail a CLI
    except Exception:
        return "unknown"


def built() -> str:
    return os.environ.get("TPUJOB_BUILD_DATE", "unknown")


def version_string() -> str:
    return f"tpu-operator {VERSION} (git {git_sha()}, built {built()})"
