from mpi_operator_tpu.scheduler.gang import GangScheduler, pod_cost
from mpi_operator_tpu.scheduler.inventory import PhysicalSlice, SliceInventory

__all__ = ["GangScheduler", "pod_cost", "PhysicalSlice", "SliceInventory"]
