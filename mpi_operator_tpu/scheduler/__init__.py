from mpi_operator_tpu.scheduler.gang import GangScheduler, pod_cost

__all__ = ["GangScheduler", "pod_cost"]
