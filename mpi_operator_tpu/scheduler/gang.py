"""Gang scheduler: atomic PodGroup admission against a finite inventory.

The reference delegates gang enforcement to Volcano — it creates a PodGroup
with minMember and trusts the external scheduler to hold pods until the gang
fits (/root/reference/v2/pkg/controller/mpi_job_controller.go:634-656,
1215-1237). On TPU the gang unit is a slice: an inherently finite, atomic
resource. This component IS the enforcement:

- **Finite inventory**: a chip budget (``chips=None`` = unbounded), or —
  the topology-aware mode — a :class:`SliceInventory` of physical slices,
  where a gang admits only when a *contiguous axis-aligned block* matching
  its host mesh is free on a physical slice (one distinct slice per job
  slice). Scattered capacity that merely sums to enough chips does NOT
  admit: fragmentation is a first-class reason to stay pending.
- **Atomic admission**: a gang is admitted only when *all* ``min_member``
  pods exist and their total cost fits the free inventory — then every pod
  is bound in one pass. Until then nothing launches; no partial placement.
- **Back-pressure, not failure**: an oversubscribed gang stays Pending with
  an ``Unschedulable`` warning event on its PodGroup (re-emitted only when
  the message changes), and is retried level-triggered as capacity frees.
- **FIFO, no backfill**: gangs are considered strictly in PodGroup creation
  order. A later, smaller gang never jumps an earlier one that is waiting
  for space — two contending jobs can never deadlock or starve each other;
  the earlier one always admits first.

Binding is spec.node_name (≙ the kube scheduler's pod binding): the
LocalExecutor launches only bound pods when ``require_binding=True``, which
is how opshell/runlocal wire it. The ICI coordinates of the placement were
already stamped on the pods by controller/placement.py; admission here is
the capacity gate in front of them.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from mpi_operator_tpu.controller.placement import (
    ANNOTATION_HOST_COORD,
    ANNOTATION_HOST_MESH,
    ANNOTATION_SLICE_ID,
)
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.events import WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    ANNOTATION_STRAGGLER_NODE,
    LOCAL_NODE,
    NODE_NAMESPACE,
    Pod,
    PodPhase,
    evict_pod,
)
from mpi_operator_tpu.opshell import metrics
from mpi_operator_tpu.machinery.cache import InformerCache
from mpi_operator_tpu.machinery.store import (
    Conflict,
    NotFound,
    ObjectStore,
    WatchEvent,
    optimistic_update,
)
from mpi_operator_tpu.scheduler.inventory import (
    SliceInventory,
    parse_node_name,
)

log = logging.getLogger("tpujob.scheduler")

LABEL_JOB_NAME = "tpujob.dev/job-name"
ENV_CHIPS_PER_HOST = "TPUJOB_CHIPS_PER_HOST"

EVENT_UNSCHEDULABLE = "Unschedulable"
EVENT_SCHEDULED = "Scheduled"
EVENT_PREEMPTED = "Preempted"
EVENT_PREEMPTING = "Preempting"

NODE_NAME = LOCAL_NODE  # single-host emulation: binding == admission

# Built-in priority classes (≙ the PriorityClass objects a k8s cluster would
# define; the reference stamps the name onto a Volcano PodGroup and relies on
# Volcano to resolve it — mpi_job_controller.go:1215-1237). Bare integer
# strings are accepted too; unknown names admit at 0 with a warning event at
# admission time (validation rejects them up front).
PRIORITY_CLASSES = {
    "": 0,
    "low": -100,
    "default": 0,
    "high": 100,
    "critical": 1000,
}


def resolve_priority_class(name: str) -> Optional[int]:
    """Priority value for a class name or integer literal; None if unknown
    (api/validation.py uses this to reject bad specs at admission)."""
    if name in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[name]
    try:
        return int(name)
    except ValueError:
        return None


def pod_cost(pod: Pod) -> int:
    """Chips a worker pod occupies while alive (its host's chip block)."""
    try:
        return max(1, int(pod.spec.container.env.get(ENV_CHIPS_PER_HOST, "1")))
    except ValueError:
        return 1


class GangScheduler:
    """Level-triggered: every Pod/PodGroup event triggers a full resync, so
    reservations are recomputed from observed state and can never drift."""

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        *,
        chips: Optional[int] = None,
        inventory: Optional[SliceInventory] = None,
        node_grace: float = 6.0,
        starvation_grace: float = 300.0,
        require_nodes: bool = False,
        preemption_grace: Optional[float] = None,
        cache: Optional["InformerCache"] = None,
    ):
        self.store = store
        # informer read path: every full-cluster list in the sync pass (Pod,
        # PodGroup, Node) comes from the watch-fed cache when one is wired —
        # the per-resync store.list round-trips were the scheduler's whole
        # store footprint. Writes (binding, eviction) still hit the store:
        # they need fresh optimistic-concurrency reads anyway.
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(store, component="tpujob-scheduler")
        self.chips = chips
        self.inventory = inventory  # topology mode; overrides the chip budget
        # scalar mode with registered Nodes: a node whose agent heartbeat is
        # older than this is not a binding target (matches the NodeMonitor)
        self.node_grace = node_grace
        # node-mode deployment (operator runs --executor none and agents run
        # the pods): binding targets are ONLY registered Nodes, never the
        # single-process 'local' sentinel. Without this, a gang submitted in
        # the operator-up/agents-not-yet window would be atomically bound to
        # 'local' — which no agent ever claims — and wedge forever, because
        # admitted gangs are never re-placed. With it, fresh gangs HOLD
        # (Unschedulable) until the first agent heartbeats in.
        self.require_nodes = require_nodes
        # OPT-IN priority preemption (None = off, the default): when the
        # capacity-blocked head of the queue has priority strictly above
        # some running gang and has been pending past this grace, the
        # minimal set of lowest-priority running gangs that frees enough
        # room is evicted whole-gang (reason=Preempted → retryable → the
        # gang-coherent restart resumes the victim from checkpoint when
        # room frees up again). ≙ the reclaim semantics the reference
        # delegates to Volcano's priorityClassName handling
        # (mpi_job_controller.go:1215-1237). Guards: never evict
        # equal-or-higher priority, and never evict anything if the
        # preemptor STILL would not fit (no thrash, no cascade).
        self.preemption_grace = preemption_grace
        # starvation guard for priority ordering: a gang pending longer than
        # this jumps to the head of the queue (FIFO among the aged), so a
        # stream of high-priority jobs cannot starve a low-priority one
        # forever
        self.starvation_grace = starvation_grace
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch_q = None
        # assume-cache (≙ kube-scheduler's assumed-pods): bindings this
        # scheduler wrote that the informer cache may not have echoed back
        # yet, keyed (ns, name) → (uid, node). Without it, the pass after
        # an admission could read the still-unbound cached copies of the
        # gang it just bound, undercount used capacity, and admit a second
        # gang onto the same chips. Entries drop once the cache observes
        # the binding (or the pod is gone/reincarnated). Only meaningful
        # with a cache; direct store reads see their own writes.
        self._assumed: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # True when the last sync saw work left to do (some gang with
        # unbound pending pods): gates the PERIODIC resync only — events
        # always wake the loop. An idle cluster does zero list traffic.
        self._dirty = True
        self._last_warning: Dict[str, str] = {}  # pg key → message (dedupe)
        # origin span of the latest watch event that woke the sync loop:
        # the scheduler.sync span's causal parent (last-writer-wins, like
        # the event coalescing itself)
        self._wake_link = None
        # pg key → when it last became pending (has unbound pods); drives
        # the starvation guard. PodGroups outlive gang restarts, so aging
        # must measure time-PENDING, not object age — a long-running job
        # that restarts is not thereby starved.
        self._pending_since: Dict[str, float] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self.cache is not None:
            # wake events must come from the INFORMER, not a separate
            # direct store watch: a direct watch can wake (and drain) this
            # loop before the cache has applied the very events it was
            # woken for — the pass reads a world with no unbound pods, sets
            # _dirty=False, and on a quiet cluster nothing ever wakes it
            # again for that (now event-silent) gang. Handler callbacks
            # fire after the cache applied the event, so a sync they wake
            # is guaranteed to observe it (same coupling the controller's
            # workqueue uses).
            import queue as _queue

            self._watch_q = _queue.Queue()
            # the informer drain sets the delivering event's origin span
            # (trace.set_delivery) around handler callbacks — capture it
            # onto the queued event so the sync it wakes can link back to
            # the write that caused it
            self.cache.add_event_handler(
                lambda etype, obj: self._watch_q.put(
                    WatchEvent(etype, obj.kind, obj,
                               trace.get_delivery())
                )
            )
        else:
            self._watch_q = self.store.watch(None)
        self._thread = threading.Thread(
            target=self._run, name="gang-scheduler", daemon=True
        )
        self._thread.start()
        self.sync()  # adopt pre-existing state

    def stop(self) -> None:
        self._stop.set()
        if self._watch_q is not None and self.cache is None:
            self.store.stop_watch(self._watch_q)

    def _run(self) -> None:
        last_sync = time.monotonic()
        while not self._stop.is_set():
            need_sync = False
            def _wakes(ev) -> bool:
                # Pod/PodGroup events always matter. Node events (uncordon,
                # agent registration, returning heartbeat) can change a
                # binding decision ONLY when some gang is waiting — agents
                # heartbeat their Node every ~2s, so ungated Node events
                # would have a 50-agent idle cluster syncing forever
                return ev.kind in ("Pod", "PodGroup") or (
                    ev.kind == "Node" and self._dirty
                )

            try:
                ev = self._watch_q.get(timeout=0.2)
                need_sync = _wakes(ev)
                if need_sync:
                    self._wake_link = getattr(ev, "trace", None)
                # COALESCE the burst: creating one 100-pod gang emits 100+
                # events, and every binding this scheduler writes emits one
                # more — syncing per event is the O(events × full-relist)
                # apiserver-load pattern the reference's redesign doc calls
                # out (proposals/scalable-robust-operator.md:90-109). Drain
                # whatever is already queued (the terminal queue.Empty ends
                # the drain) and run ONE sync for the lot; level-triggered
                # semantics make this safe — sync reads current state, not
                # the events.
                while True:
                    ev = self._watch_q.get_nowait()
                    if _wakes(ev):
                        need_sync = True
                        self._wake_link = getattr(ev, "trace", None)
            except queue.Empty:
                pass
            if not need_sync and time.monotonic() - last_sync < 2.0:
                continue
            if not need_sync and not self._dirty:
                # periodic resync exists ONLY because a node going stale
                # emits no event (the absence of heartbeats) — which can
                # change nothing unless some gang is waiting to bind. With
                # nothing pending, the idle cluster does zero list traffic.
                continue
            try:
                self.sync()
                last_sync = time.monotonic()
            except Exception:
                # keep the loop alive AND keep retrying: a transient store
                # error (e.g. SQLITE_BUSY) must not strand a pending gang —
                # with _dirty stale-False and the event already drained, no
                # later wakeup would come
                self._dirty = True
                log.exception("scheduler sync failed")

    # -- accounting ---------------------------------------------------------

    def used_chips(self, pods: Optional[List[Pod]] = None) -> int:
        """Chips held by live bound pods. Pass the current pass's (assume-
        overlaid) snapshot inside a sync — a fresh cache read here could
        miss this scheduler's own un-echoed bindings and undercount."""
        if pods is None:
            # list OUTSIDE the lock (LCK001): self.read may be a real store
            # over HTTP, and a network round-trip under the scheduler lock
            # would stall every concurrent sync/accounting caller; only the
            # assumed-binding overlay needs the lock (read-only — this
            # snapshot may be stale relative to a concurrent sync's fresh
            # assumptions, so it must never retire them)
            pods = self.read.list("Pod")
            with self._lock:
                self._overlay_assumed(pods, retire=False)
        return sum(
            pod_cost(p)
            for p in pods
            if p.spec.node_name and not p.is_finished()
        )

    def free_chips(self, pods: Optional[List[Pod]] = None) -> Optional[int]:
        if self.chips is None:
            return None
        return self.chips - self.used_chips(pods)

    def occupancy(self, pods: Optional[List[Pod]] = None) -> Dict[str, set]:
        """Topology mode: physical-slice host coords held by live bound pods
        (recomputed each pass — nothing to drift; same snapshot rule as
        used_chips)."""
        if pods is None:
            # same LCK001 discipline as used_chips: the read round-trip must
            # not ride the scheduler lock, and the stale-snapshot overlay
            # must not retire assumptions
            pods = self.read.list("Pod")
            with self._lock:
                self._overlay_assumed(pods, retire=False)
        occ: Dict[str, set] = {}
        for p in pods:
            if not p.spec.node_name or p.is_finished():
                continue
            parsed = parse_node_name(p.spec.node_name)
            if parsed is not None:
                occ.setdefault(parsed[0], set()).add(parsed[1])
        return occ

    # -- the scheduling pass ------------------------------------------------

    def sync(self) -> None:
        if self.cache is not None and not self.cache.has_synced():
            # a cold cache looks like an empty cluster: admitting against
            # phantom-free capacity (or healing "local" bindings that are
            # merely unobserved yet) would be acting on a world that is not
            # there. Stay dirty so the periodic resync retries until the
            # initial snapshot lands (≙ WaitForCacheSync).
            self._dirty = True
            return
        link, self._wake_link = self._wake_link, None
        t0 = time.perf_counter()
        with trace.start_span("scheduler.sync", parent=link):
            with self._lock:
                self._sync_locked()
        metrics.scheduler_sync_latency.observe(time.perf_counter() - t0)

    def _overlay_assumed(self, pods: List[Pod], retire: bool = True) -> None:
        """Apply not-yet-echoed bindings onto the cached pod snapshot and
        (when ``retire``) drop assumptions the cache has caught up with.
        Accessor paths (used_chips/occupancy) pass ``retire=False``: their
        snapshot is taken OUTSIDE the lock and may predate a concurrent
        sync's fresh assumption — retiring from a stale snapshot would
        re-open the capacity double-bind _assumed exists to prevent. Only
        _sync_locked, whose snapshot is taken under the lock it holds,
        may retire."""
        if not self._assumed:
            return
        present: Dict[Tuple[str, str], Pod] = {}
        for p in pods:
            present[(p.metadata.namespace, p.metadata.name)] = p
        for key, (uid, node) in list(self._assumed.items()):
            cur = present.get(key)
            if cur is None or cur.metadata.uid != uid:
                # pod gone or a new incarnation under the same name: the
                # assumption must not shadow-bind an object it never bound
                if retire:
                    del self._assumed[key]
            elif cur.spec.node_name:
                if retire:
                    del self._assumed[key]  # echo landed
            else:
                cur.spec.node_name = node  # still in flight: overlay

    def _sync_locked(self) -> None:
        pods = self.read.list("Pod")
        self._overlay_assumed(pods)
        by_gang: Dict[Tuple[str, str], List[Pod]] = defaultdict(list)
        for p in pods:
            job = p.metadata.labels.get(LABEL_JOB_NAME, "")
            if job:
                by_gang[(p.metadata.namespace, job)].append(p)

        occ = None  # topology occupancy, computed once on first use
        # scalar mode turns node-aware the moment agents register Nodes:
        # binding targets become live nodes (≙ kubelets posting NodeStatus)
        # instead of the single-process 'local' sentinel
        nodes: Optional[List] = None
        node_used: Dict[str, int] = {}
        if self.inventory is None:
            all_nodes = self._list_nodes_readonly()
            if self.require_nodes:
                # heal any 'local'-sentinel bindings (pre-upgrade state or a
                # misconfigured operator). In a node-mode deployment no
                # local executor exists by construction (opshell rejects the
                # combination), so NOTHING can run a 'local'-bound pod:
                # PENDING ones are unbound to re-place onto real nodes;
                # RUNNING ones are orphans from a pre-upgrade single-host
                # operator — the store says Running but no process backs it;
                # left alone they would hold chip budget forever. Evict them
                # (retryable → gang-coherent restart onto real nodes). Runs
                # BEFORE any accounting so healed pods are not counted
                # against this very pass's chip budget.
                for p in pods:
                    if p.spec.node_name != NODE_NAME or p.is_finished():
                        continue
                    if p.status.phase == PodPhase.PENDING:
                        if self._unbind(p):
                            p.spec.node_name = ""  # pass sees it unbound
                    elif evict_pod(
                        self.store, p,
                        "bound to the 'local' sentinel in a node-mode "
                        "deployment; no executor can run it",
                    ):
                        # pass sees it finished (not holding capacity)
                        p.status.phase = PodPhase.FAILED
                        p.status.reason = "Evicted"
            if all_nodes or self.require_nodes:
                nodes = self._live_nodes(all_nodes)
                node_used = self._node_used(pods)
        free = self.free_chips(pods)  # None = unbounded
        # (priority desc, FIFO) with an aging guard: aged gangs go first in
        # plain FIFO order — the queue the reference delegates to Volcano's
        # priorityClassName handling (mpi_job_controller.go:1215-1237),
        # implemented here because admission IS this component
        now = time.time()
        all_groups = self.read.list("PodGroup")
        keys = set()
        for pg in all_groups:
            key = self._pg_key(pg)
            keys.add(key)
            job = pg.metadata.labels.get(LABEL_JOB_NAME, pg.metadata.name)
            members = by_gang.get((pg.metadata.namespace, job), [])
            if any(
                not p.spec.node_name
                and p.status.phase == PodPhase.PENDING
                and not p.is_finished()
                for p in members
            ):
                self._pending_since.setdefault(key, now)
            else:
                self._pending_since.pop(key, None)
        for stale in set(self._pending_since) - keys:
            self._pending_since.pop(stale, None)  # deleted gangs don't leak

        def order(pg):
            key = self._pg_key(pg)
            ts = pg.metadata.creation_timestamp or 0
            pri = resolve_priority_class(pg.spec.priority_class)
            if pri is None:
                pri = 0  # validation rejects these; stored legacy admits at 0
            since = self._pending_since.get(key, now)
            if now - since > self.starvation_grace:
                return (0, 0, since, pg.metadata.name)
            return (1, -pri, ts, pg.metadata.name)

        groups = sorted(all_groups, key=order)
        # the fresh gang that capacity-blocked the FIFO this pass (if any):
        # the preemption candidate — by construction the highest-priority
        # gang that cannot currently fit
        blocked: Optional[Tuple] = None
        for pg in groups:
            job = pg.metadata.labels.get(LABEL_JOB_NAME, pg.metadata.name)
            members = by_gang.get((pg.metadata.namespace, job), [])
            live = [p for p in members if not p.is_finished()]
            bound = [p for p in live if p.spec.node_name]
            unbound = [
                p
                for p in live
                if not p.spec.node_name and p.status.phase == PodPhase.PENDING
            ]
            if not unbound:
                continue
            if self.inventory is not None:
                if occ is None:
                    occ = self.occupancy(pods)
                    self._occlude_dead_nodes(occ)
                if not self._sync_gang_topology(pg, bound, unbound, occ):
                    if not bound:
                        blocked = (pg, unbound)
                    break  # strict FIFO, same as the scalar branch below
                continue
            if bound:
                # gang already admitted: later members (elastic scale-up /
                # evicted-member relaunch) bind individually as capacity allows
                for p in unbound:
                    cost = pod_cost(p)
                    if free is not None and cost > free:
                        self._warn(
                            pg,
                            f"scale-up pod {p.metadata.name} needs {cost} "
                            f"chips, {free} free",
                        )
                        break
                    target = NODE_NAME
                    if nodes is not None:
                        target = self._pick_node(nodes, node_used, cost)
                        if target is None:
                            self._warn(
                                pg,
                                f"scale-up pod {p.metadata.name} needs {cost} "
                                f"chips but no live node has room",
                            )
                            break
                    if self._bind(p, target):
                        if free is not None:
                            free -= cost
                        node_used[target] = node_used.get(target, 0) + cost
                continue
            # fresh gang: all-or-nothing
            if len(unbound) < pg.spec.min_member:
                # controller hasn't created the full gang yet; wait
                continue
            total = sum(pod_cost(p) for p in unbound)
            if free is not None and total > free:
                self._warn(
                    pg,
                    f"gang needs {total} chips ({len(unbound)} pods), "
                    f"{free} of {self.chips} free",
                )
                # strict FIFO: do not backfill later gangs past this one —
                # a stream of small jobs could otherwise starve a large one
                blocked = (pg, unbound)
                break
            assignment = None
            if nodes is not None:
                assignment = self._assign_gang(nodes, node_used, unbound)
                if assignment is None:
                    self._warn(
                        pg,
                        f"gang needs {total} chips ({len(unbound)} pods) but "
                        f"no placement fits the {len(nodes)} live node(s)",
                    )
                    blocked = (pg, unbound)
                    break  # capacity: hold the FIFO, same as the budget path
            n = 0
            for p in unbound:
                target = assignment[p.metadata.name] if assignment else NODE_NAME
                if self._bind(p, target):
                    n += 1
                    if free is not None:
                        free -= pod_cost(p)
                    node_used[target] = node_used.get(target, 0) + pod_cost(p)
            self._last_warning.pop(self._pg_key(pg), None)
            self.recorder.event(
                pg, "Normal", EVENT_SCHEDULED,
                f"gang admitted: {n} pods, {sum(pod_cost(p) for p in unbound)} chips",
            )
        if blocked is not None:
            # pods/all_groups are THIS pass's snapshots (no extra store
            # round-trips), and deliberately stale with respect to bindings
            # made during the pass: a gang admitted seconds ago in this very
            # pass still looks unbound in the snapshot and therefore can
            # never be selected as a victim — an aged low-priority gang that
            # admitted ahead of the blocked head is not admit-then-evicted
            # in the same breath
            self._maybe_preempt(
                blocked[0], blocked[1], free, nodes, node_used, occ,
                pods, all_groups,
            )
        # gangs bound this pass keep their pending_since entry until the
        # next pass observes them bound — one extra periodic sync, then the
        # idle cluster goes quiet
        self._dirty = bool(self._pending_since)

    # -- priority preemption ------------------------------------------------

    def _maybe_preempt(
        self,
        pg,
        unbound: List[Pod],
        free: Optional[int],
        nodes: Optional[List],
        node_used: Dict[str, int],
        occ: Optional[Dict[str, set]],
        pods: List[Pod],
        all_groups: List,
    ) -> None:
        """Evict the minimal set of strictly-lower-priority running gangs
        that lets the capacity-blocked queue head fit. Opt-in
        (preemption_grace), whole-gang (reason=Preempted → retryable → the
        victim's gang-coherent restart resumes from checkpoint later), and
        guarded: nothing is evicted if even evicting EVERY lower-priority
        gang would not make room (no thrash), and equal-or-higher priority
        is never touched. Binding happens on the NEXT pass, level-triggered
        off the eviction events — this pass only frees the room."""
        if self.preemption_grace is None:
            return
        key = self._pg_key(pg)
        since = self._pending_since.get(key)
        now = time.time()
        if since is None or now - since < self.preemption_grace:
            return
        pri = resolve_priority_class(pg.spec.priority_class)
        if pri is None:
            pri = 0
        # admitted gangs of strictly lower priority, with their live bound
        # pods (what actually holds capacity) — from the caller's pass
        # snapshots (see the call site for why staleness is a feature)
        by_gang: Dict[Tuple[str, str], List[Pod]] = defaultdict(list)
        for p in pods:
            job = p.metadata.labels.get(LABEL_JOB_NAME, "")
            if job and p.spec.node_name and not p.is_finished():
                by_gang[(p.metadata.namespace, job)].append(p)
        pool = []
        for v in all_groups:
            if self._pg_key(v) == key:
                continue
            vpri = resolve_priority_class(v.spec.priority_class)
            if vpri is None:
                vpri = 0
            if vpri >= pri:
                continue  # never preempt equal-or-higher priority
            vjob = v.metadata.labels.get(LABEL_JOB_NAME, v.metadata.name)
            held = by_gang.get((v.metadata.namespace, vjob), [])
            if not held:
                continue
            pool.append((vpri, v, held))
        # cheapest victims first: lowest priority, then youngest (evicting
        # the most recently admitted loses the least progress), name-stable
        pool.sort(key=lambda t: (
            t[0], -(t[1].metadata.creation_timestamp or 0), t[1].metadata.name
        ))
        chosen: List[Tuple[int, object, List[Pod]]] = []
        for item in pool:
            chosen.append(item)
            if self._fits_after_eviction(
                unbound, [held for _, _, held in chosen],
                free, nodes, node_used, occ,
            ):
                break
        else:
            return  # still would not fit: evict nothing
        # prune-back to a MINIMAL victim set: greedy accumulation can pick
        # up collateral whose eviction contributes nothing (a tiny lowest-
        # priority gang on a node that could never host the preemptor
        # anyway) — drop any member whose removal still leaves a fit, so no
        # gang suffers a useless restart
        for item in list(chosen):
            if len(chosen) == 1:
                break
            trial = [v for v in chosen if v is not item]
            if self._fits_after_eviction(
                unbound, [held for _, _, held in trial],
                free, nodes, node_used, occ,
            ):
                chosen = trial
        names = ", ".join(self._pg_key(v) for _, v, _ in chosen)
        log.warning(
            "preempting %s for %s (priority %d, pending %.0fs)",
            names, key, pri, now - since,
        )
        for vpri, victim, held in chosen:
            n = 0
            for p in held:
                if evict_pod(
                    self.store, p,
                    f"preempted by {key} (priority {pri} > {vpri})",
                    reason="Preempted",  # retryable, but does NOT burn
                    # the victim's backoffLimit (controller exempts it)
                ):
                    n += 1
            # reset the victim's pending clock: if it was starvation-AGED,
            # its recreated pods would otherwise jump the queue ahead of the
            # very gang that preempted it and be preempted again — an
            # admit/evict livelock that burns the victim's restart budget
            # while the preemptor starves. Preemption means priority beats
            # aging; the victim re-queues with a fresh clock.
            self._pending_since.pop(self._pg_key(victim), None)
            self.recorder.event(
                victim, WARNING, EVENT_PREEMPTED,
                f"gang preempted ({n} pods evicted) by higher-priority "
                f"{key}; will restart when capacity frees",
            )
            metrics.gangs_preempted.inc()
        self.recorder.event(
            pg, "Normal", EVENT_PREEMPTING,
            f"preempting lower-priority {names} after {now - since:.0f}s "
            f"pending",
        )

    def _fits_after_eviction(
        self,
        unbound: List[Pod],
        victim_pod_lists: List[List[Pod]],
        free: Optional[int],
        nodes: Optional[List],
        node_used: Dict[str, int],
        occ: Optional[Dict[str, set]],
    ) -> bool:
        """Would the blocked gang fit if these victims' pods were gone?
        Simulated on scratch copies in whichever admission mode is active —
        the same placement logic the real pass will run next sync."""
        victims = [p for lst in victim_pod_lists for p in lst]
        if self.inventory is not None:
            occ2 = {k: set(v) for k, v in (occ or {}).items()}
            for p in victims:
                parsed = parse_node_name(p.spec.node_name)
                if parsed is not None:
                    occ2.get(parsed[0], set()).discard(parsed[1])
            # dead-node slots must stay occluded even after their pods left
            self._occlude_dead_nodes(occ2)
            geos = {p.metadata.name: self._pod_geometry(p) for p in unbound}
            if any(g is None for g in geos.values()):
                return False
            mesh = next(iter(geos.values()))[0]
            num_slices = 1 + max(g[2] for g in geos.values())
            return (
                self.inventory.find_placement(mesh, num_slices, occ2)
                is not None
            )
        freed = sum(pod_cost(p) for p in victims)
        total = sum(pod_cost(p) for p in unbound)
        if free is not None and total > free + freed:
            return False
        if nodes is not None:
            used2 = dict(node_used)
            for p in victims:
                node = p.spec.node_name
                used2[node] = max(0, used2.get(node, 0) - pod_cost(p))
            return self._assign_gang(nodes, used2, unbound) is not None
        return free is not None

    # -- topology-aware admission -------------------------------------------

    @staticmethod
    def _pod_geometry(pod: Pod):
        """(host_mesh, host_coord, slice_id) from the placement annotations
        controller/placement.py stamped; None when absent (non-topology pod)."""
        ann = pod.metadata.annotations
        mesh = ann.get(ANNOTATION_HOST_MESH, "")
        coord = ann.get(ANNOTATION_HOST_COORD, "")
        if not mesh or not coord:
            return None
        try:
            return (
                tuple(int(d) for d in mesh.split("x")),
                tuple(int(d) for d in coord.split("x")),
                int(ann.get(ANNOTATION_SLICE_ID, "0")),
            )
        except ValueError:
            return None

    def _sync_gang_topology(
        self, pg, bound: List[Pod], unbound: List[Pod], occ: Dict[str, set]
    ) -> bool:
        """One gang against the slice inventory (``occ`` is the pass-wide
        occupancy, updated in place as binds land). Returns False when the
        gang must keep waiting for capacity (caller stops the FIFO pass)."""
        assert self.inventory is not None
        geos = {p.metadata.name: self._pod_geometry(p) for p in unbound}
        if any(g is None for g in geos.values()):
            self._warn(pg, "pods carry no placement annotations; cannot admit")
            return True  # not capacity — skip, don't block the queue
        if bound:
            # relaunched/scaled member of an admitted gang: rejoin the
            # gang's existing block (offset = bound member's abs − its coord)
            offsets = {}
            for b in bound:
                parsed = parse_node_name(b.spec.node_name)
                geo = self._pod_geometry(b)
                if parsed is None or geo is None:
                    continue
                name, abs_coord = parsed
                offsets[geo[2]] = (
                    name,
                    tuple(a - c for a, c in zip(abs_coord, geo[1])),
                )
            ok = True
            for p in unbound:
                mesh, coord, sid = geos[p.metadata.name]
                if sid not in offsets:
                    ok = False
                    continue
                name, off = offsets[sid]
                node = self.inventory.node_for(name, off, coord)
                if node is None:
                    # annotations outgrew the admitted block (e.g. rescale):
                    # the gang-coherent restart will re-admit; never bind to
                    # a host outside the physical mesh
                    ok = False
                    continue
                parsed = parse_node_name(node)
                if parsed and parsed[1] in occ.get(name, set()):
                    # the freed slot was taken by another gang meanwhile:
                    # this member cannot rejoin. Warn and skip — holding the
                    # whole FIFO here would starve unrelated gangs behind a
                    # non-capacity conflict.
                    self._warn(
                        pg, f"pod {p.metadata.name}'s slot {node} is occupied"
                    )
                    continue
                if self._bind(p, node):
                    occ.setdefault(name, set()).add(parsed[1])
            if not ok:
                self._warn(pg, "gang grew past its admitted block; waiting "
                               "for the gang-coherent restart to re-admit")
            return True
        if len(unbound) < pg.spec.min_member:
            return True  # gang not fully created yet; don't block the queue
        mesh = next(iter(geos.values()))[0]
        num_slices = 1 + max(g[2] for g in geos.values())
        placement = self.inventory.find_placement(mesh, num_slices, occ)
        if placement is None:
            if self.inventory.find_placement(mesh, num_slices, {}) is None:
                # can NEVER fit (wrong dimensionality / bigger than every
                # physical slice): a spec problem, not a capacity wait —
                # skip so it doesn't starve the gangs behind it forever
                self._warn(
                    pg,
                    f"host mesh {'x'.join(map(str, mesh))} x{num_slices} "
                    f"slice(s) can never fit this inventory — not admitting",
                )
                return True
            self._warn(
                pg,
                f"no contiguous {'x'.join(map(str, mesh))} host block free "
                f"on {num_slices} distinct slice(s) — waiting (fragmentation "
                f"counts: scattered free hosts cannot carry ICI collectives)",
            )
            return False  # capacity/topology: hold FIFO here
        n = 0
        for p in unbound:
            _, coord, sid = geos[p.metadata.name]
            name, off = placement[sid]
            node = self.inventory.node_for(name, off, coord)
            if node is not None and self._bind(p, node):
                n += 1
                parsed = parse_node_name(node)
                if parsed:
                    occ.setdefault(parsed[0], set()).add(parsed[1])
        self._last_warning.pop(self._pg_key(pg), None)
        where = ", ".join(
            s + "+" + "x".join(map(str, o)) for s, o in placement
        )
        self.recorder.event(
            pg, "Normal", EVENT_SCHEDULED,
            f"gang admitted: {n} pods in {'x'.join(map(str, mesh))} "
            f"block(s) at {where}",
        )
        return True

    def _occlude_dead_nodes(self, occ: Dict[str, set]) -> None:
        """Inventory mode with registered agents: mark the host slot of any
        registered-but-not-live Node as occupied, so the block search routes
        around dead hardware. Without this, a gang evicted off a dead node
        would be re-placed onto the same free-looking slot and bounce
        through evict/restart until backoffLimit kills the job. Hosts with
        no registered agent stay schedulable (pure-inventory deployments
        carry no Node objects at all)."""
        all_nodes = self._list_nodes_readonly()
        if not all_nodes:
            return
        live = {n.metadata.name for n in self._live_nodes(all_nodes)}
        for n in all_nodes:
            if n.metadata.name in live:
                continue
            parsed = parse_node_name(n.metadata.name)
            if parsed is not None:
                occ.setdefault(parsed[0], set()).add(parsed[1])

    # -- scalar node mode ---------------------------------------------------

    def _list_nodes_readonly(self) -> List:
        """Node snapshot for scoring — READ-ONLY by contract. Through the
        informer this skips the per-object deepcopy (10k-job round: 1k
        Nodes × 5 passes/s of copying dominated the leader's GIL; the
        scheduler only reads capacity/ready/heartbeat off Nodes and never
        mutates or retains them). Raw-store reads keep their own copies."""
        if self.cache is not None:
            return self.read.list("Node", NODE_NAMESPACE, copy=False)
        return self.read.list("Node", NODE_NAMESPACE)

    def _live_nodes(self, all_nodes: List) -> List:
        """Ready nodes with a fresh heartbeat (or static: heartbeat 0),
        name-sorted for deterministic spread."""
        now = time.time()
        out = []
        for n in all_nodes:
            if not n.status.ready or n.status.unschedulable:
                continue  # dead/drained OR cordoned: not a binding target
            hb = n.status.last_heartbeat
            if hb and now - hb > self.node_grace:
                continue
            out.append(n)
        return sorted(out, key=lambda n: n.metadata.name)

    @staticmethod
    def _node_used(pods: List[Pod]) -> Dict[str, int]:
        used: Dict[str, int] = defaultdict(int)
        for p in pods:
            if p.spec.node_name and not p.is_finished():
                used[p.spec.node_name] += pod_cost(p)
        return used

    @staticmethod
    def _pick_node(nodes: List, used: Dict[str, int], cost: int) -> Optional[str]:
        """Least-loaded live node with room (spread; name order breaks
        ties), in three preference tiers. Nodes with a pending maintenance
        notice are LAST-RESORT: placing a migration onto the next victim
        would just move it twice (the disruption plane's anti-hop
        penalty) — they only host when no clean node has room. Nodes
        carrying the rescheduler's straggler flag (suspected-slow
        hardware, ISSUE 18) sit in the MIDDLE tier: a gang moved off sick
        hardware must not land right back on it, but a flagged node is
        still better than one the fleet is about to lose."""
        best = best_load = None
        flagged_best = flagged_load = None
        doomed_best = doomed_load = None
        for n in nodes:
            cap = n.status.capacity_chips
            u = used.get(n.metadata.name, 0)
            if cap is not None and u + cost > cap:
                continue
            if ANNOTATION_MAINTENANCE_AT in n.metadata.annotations:
                if doomed_best is None or u < doomed_load:
                    doomed_best, doomed_load = n.metadata.name, u
                continue
            if ANNOTATION_STRAGGLER_NODE in n.metadata.annotations:
                if flagged_best is None or u < flagged_load:
                    flagged_best, flagged_load = n.metadata.name, u
                continue
            if best is None or u < best_load:
                best, best_load = n.metadata.name, u
        if best is not None:
            return best
        if flagged_best is not None:
            return flagged_best
        return doomed_best

    def _assign_gang(
        self, nodes: List, used: Dict[str, int], unbound: List[Pod]
    ) -> Optional[Dict[str, str]]:
        """All-or-nothing pod→node assignment for a fresh gang: greedy
        least-loaded spread simulated on a scratch copy, committed only when
        every member fits (gang semantics — no partial placement). Pods are
        taken in name order so worker 0 lands deterministically."""
        scratch = dict(used)
        out: Dict[str, str] = {}
        for p in sorted(unbound, key=lambda p: p.metadata.name):
            cost = pod_cost(p)
            target = self._pick_node(nodes, scratch, cost)
            if target is None:
                return None
            scratch[target] = scratch.get(target, 0) + cost
            out[p.metadata.name] = target
        return out

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _pg_key(pg) -> str:
        return f"{pg.metadata.namespace}/{pg.metadata.name}"

    def _warn(self, pg, message: str) -> None:
        key = self._pg_key(pg)
        if self._last_warning.get(key) == message:
            return
        self._last_warning[key] = message
        self.recorder.event(pg, WARNING, EVENT_UNSCHEDULABLE, message)

    def _unbind(self, pod: Pod) -> bool:
        """Clear a 'local'-sentinel binding (require_nodes healing only).
        Optimistic: a local executor launching the pod (RUNNING) between
        read and write must win — a forced write would revert its phase and
        make the job run twice. Only a pod still PENDING and 'local'-bound
        at write time is safe to re-place: nothing has ever run it."""
        def mutate(cur) -> bool:
            if cur.spec.node_name != NODE_NAME or cur.is_finished():
                return False
            if cur.status.phase != PodPhase.PENDING:
                return False
            cur.spec.node_name = ""
            return True

        ok = optimistic_update(
            self.store, "Pod", pod.metadata.namespace, pod.metadata.name,
            mutate, what="unbind-local",
        ) is not None
        if ok:
            self._assumed.pop(
                (pod.metadata.namespace, pod.metadata.name), None
            )
            log.info(
                "unbound %s/%s from the 'local' sentinel (node-mode deployment)",
                pod.metadata.namespace, pod.metadata.name,
            )
        return ok

    def _bind(self, pod: Pod, node: str = NODE_NAME) -> bool:
        """Set node_name (scheduler owns this field, like the kube binding
        subresource) via an rv-guarded merge-patch: ONE request against
        the pass's snapshot rv in the common case — the old GET +
        force-PUT pair not only cost two round-trips, its force write
        could clobber anything (an eviction, a status mirror) that landed
        between them; the rv precondition turns that race into a Conflict
        we re-check."""
        ns, name = pod.metadata.namespace, pod.metadata.name

        def attempt(rv: int):
            return self.store.patch(
                "Pod", ns, name,
                {"metadata": {"resource_version": rv},
                 "spec": {"node_name": node}},
            )

        # the bind span lives in the JOB's trace (the pod carries the
        # job's trace-id annotation) with the scheduler.sync pass as its
        # causal parent; its latency is the admission hot path PERF
        # tracks, observed where the span closes
        t0 = time.perf_counter()
        with trace.start_span(
            "scheduler.bind",
            trace_id=pod.metadata.annotations.get(trace.ANNOTATION_TRACE_ID),
            attrs={"pod": f"{ns}/{name}", "node": node},
        ) as sp:
            try:
                committed = attempt(pod.metadata.resource_version)
            except NotFound:
                return False
            except Conflict:
                # snapshot went stale (executor mirror, eviction, another
                # writer): re-read once and re-check the binding
                # precondition
                cur = self.store.try_get("Pod", ns, name)
                if cur is None or cur.spec.node_name or cur.is_finished():
                    return False
                try:
                    committed = attempt(cur.metadata.resource_version)
                except (NotFound, Conflict):
                    return False  # level-triggered: the next pass retries
            sp.set_attr("rv", committed.metadata.resource_version)
        metrics.scheduler_bind_latency.observe(time.perf_counter() - t0)
        if self.cache is not None:
            # remember the binding until the informer echoes it back — the
            # next pass's cached snapshot must not undercount this gang
            self._assumed[(ns, name)] = (committed.metadata.uid, node)
        return True
