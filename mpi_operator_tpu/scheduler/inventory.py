"""Slice-shaped inventory: the topology the gang scheduler admits against.

A scalar chip budget can say "8 chips free" but not "those 8 chips form two
2x2 corners of different slices" — and on real hardware a 4x2 job cannot run
on scattered chips: its collectives must ride contiguous ICI (SURVEY.md §7
"hard parts": ICI-aware placement; the capability bar is the reference's
Volcano delegation, mpi_job_controller.go:634-656, which has no topology
model at all).

The model here:

- The cluster is a list of **physical slices**, each a host mesh (e.g. two
  v5e-16 slices → ``4x4,4x4`` with 4-chip hosts). Hosts, not chips, are the
  allocation unit — a TPU host's chip block is indivisible.
- A job's gang needs ``num_slices`` **contiguous, axis-aligned blocks** of
  shape ``host_mesh`` (from controller/placement.py), each on a distinct
  physical slice (job slices talk DCN; hosts within a block talk ICI).
- Admission is an exact-orientation block search per physical slice.
  Occupancy is recomputed from bound pods every pass (level-triggered — the
  scheduler carries no state that can drift).

``parse("4x4,4x4")`` builds the inventory; a bound pod's node name is
``slice<i>/<abs-coord>`` so occupancy round-trips through the store.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

Coord = Tuple[int, ...]


@dataclass(frozen=True)
class PhysicalSlice:
    name: str
    host_mesh: Tuple[int, ...]

    @property
    def num_hosts(self) -> int:
        n = 1
        for d in self.host_mesh:
            n *= d
        return n


def _node_name(slice_name: str, coord: Coord) -> str:
    return f"{slice_name}/{'x'.join(map(str, coord))}"


def parse_node_name(node: str) -> Optional[Tuple[str, Coord]]:
    """Inverse of the binding's node name; None for foreign names (e.g. the
    scalar mode's ``local``)."""
    if "/" not in node:
        return None
    name, _, coord = node.partition("/")
    try:
        return name, tuple(int(p) for p in coord.split("x"))
    except ValueError:
        return None


class SliceInventory:
    """The physical slices a scheduler instance owns."""

    def __init__(self, slices: Sequence[PhysicalSlice]):
        self.slices = list(slices)
        by_name = {s.name for s in self.slices}
        if len(by_name) != len(self.slices):
            raise ValueError("physical slice names must be unique")

    @staticmethod
    def parse(spec: str) -> "SliceInventory":
        """``"4x4,4x4"`` → two 4x4-host slices named slice0, slice1."""
        slices = []
        for i, part in enumerate(p.strip() for p in spec.split(",") if p.strip()):
            try:
                mesh = tuple(int(d) for d in part.split("x"))
            except ValueError:
                raise ValueError(f"bad host mesh {part!r}") from None
            if not mesh or any(d < 1 for d in mesh):
                raise ValueError(f"bad host mesh {part!r}")
            slices.append(PhysicalSlice(name=f"slice{i}", host_mesh=mesh))
        if not slices:
            raise ValueError(f"empty inventory spec {spec!r}")
        return SliceInventory(slices)

    @property
    def total_hosts(self) -> int:
        return sum(s.num_hosts for s in self.slices)

    # -- the block search ---------------------------------------------------

    @staticmethod
    def _free_block_at(
        occupied: Set[Coord], offset: Coord, shape: Coord
    ) -> bool:
        for rel in itertools.product(*(range(d) for d in shape)):
            if tuple(o + r for o, r in zip(offset, rel)) in occupied:
                return False
        return True

    def _find_block(
        self, phys: PhysicalSlice, occupied: Set[Coord], shape: Coord
    ) -> Optional[Coord]:
        """Smallest-offset free axis-aligned block of ``shape`` in ``phys``
        (exact orientation: ICI axes are not interchangeable)."""
        if len(shape) != len(phys.host_mesh):
            return None
        if any(s > m for s, m in zip(shape, phys.host_mesh)):
            return None
        for offset in itertools.product(
            *(range(m - s + 1) for s, m in zip(shape, phys.host_mesh))
        ):
            if self._free_block_at(occupied, offset, shape):
                return offset
        return None

    def find_placement(
        self,
        host_mesh: Coord,
        num_slices: int,
        occupancy: Dict[str, Set[Coord]],
    ) -> Optional[List[Tuple[str, Coord]]]:
        """Atomically place ``num_slices`` blocks of ``host_mesh`` on
        DISTINCT physical slices. Returns [(slice_name, offset)] per job
        slice, or None when no placement exists (caller keeps the gang
        pending — fragmentation is a valid reason even when total free
        hosts would suffice)."""
        chosen: List[Tuple[str, Coord]] = []
        used_slices: Set[str] = set()
        for _ in range(num_slices):
            found = None
            for phys in self.slices:
                if phys.name in used_slices:
                    continue
                off = self._find_block(
                    phys, occupancy.get(phys.name, set()), host_mesh
                )
                if off is not None:
                    found = (phys.name, off)
                    break
            if found is None:
                return None
            chosen.append(found)
            used_slices.add(found[0])
        return chosen

    def node_for(
        self, slice_name: str, offset: Coord, host_coord: Coord
    ) -> Optional[str]:
        """The node name binding a worker at ``host_coord`` within its job
        block placed at ``offset`` — or None when the host falls outside the
        physical slice (a rejoining pod whose annotations no longer match
        the admitted block must not bind to a host that doesn't exist)."""
        phys = next((s for s in self.slices if s.name == slice_name), None)
        if phys is None:
            return None
        coord = tuple(o + c for o, c in zip(offset, host_coord))
        if len(coord) != len(phys.host_mesh) or any(
            c < 0 or c >= m for c, m in zip(coord, phys.host_mesh)
        ):
            return None
        return _node_name(slice_name, coord)
