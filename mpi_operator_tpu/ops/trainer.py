"""Distributed trainer: one global-view jit train step.

≙ Horovod's ``DistributedOptimizer`` + ``broadcast_global_variables``
(/root/reference/examples/horovod/tensorflow_mnist.py, SURVEY.md §2.5), made
TPU-native: instead of wrapping an optimizer with an explicit allreduce hook,
the step is compiled once over the whole mesh with the batch sharded along
(data, fsdp) and params laid out by the model's logical axes — XLA derives
the gradient reductions from the shardings and fuses them into the backward
pass (reduce-scatter/all-gather on ICI for fsdp, all-reduce for pure data).
The initial-broadcast problem disappears: params are initialized once,
globally, by a jitted init.

Works for stateless models (llama, mnist: ``loss_fn(params, batch)``) and
stateful ones (resnet: ``loss_fn(params, state, batch) -> (loss, new_state)``
via ``has_model_state=True``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from mpi_operator_tpu.parallel.sharding import (
    Rules,
    logical_spec,
    mesh_filtered_spec,
)


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    learning_rate: float = 1e-3
    warmup_steps: int = 0
    total_steps: int = 0  # 0 = constant lr after warmup
    weight_decay: float = 0.0
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip_norm: float = 1.0
    optimizer: str = "adamw"  # or "sgd", "momentum"
    momentum: float = 0.9
    remat: bool = False  # jax.checkpoint the loss fn (trade FLOPs for HBM)
    # adamw only: store the first moment in bf16 — halves its HBM footprint
    # and per-step traffic for ~1 ulp of update noise (the second moment
    # stays f32: its rsqrt is precision-sensitive)
    adam_mu_bf16: bool = False


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any  # {} when the model is stateless


def _schedule(config: TrainerConfig) -> optax.Schedule:
    if config.warmup_steps == 0 and config.total_steps == 0:
        return optax.constant_schedule(config.learning_rate)
    if config.total_steps:
        return optax.warmup_cosine_decay_schedule(
            0.0, config.learning_rate, config.warmup_steps,
            max(config.total_steps, config.warmup_steps + 1),
        )
    return optax.linear_schedule(0.0, config.learning_rate, max(config.warmup_steps, 1))


def _optimizer(config: TrainerConfig) -> optax.GradientTransformation:
    sched = _schedule(config)
    if config.optimizer == "adamw":
        opt = optax.adamw(
            sched, b1=config.beta1, b2=config.beta2,
            weight_decay=config.weight_decay,
            mu_dtype=jnp.bfloat16 if config.adam_mu_bf16 else None,
        )
    elif config.optimizer == "momentum":
        opt = optax.sgd(sched, momentum=config.momentum)
    elif config.optimizer == "sgd":
        opt = optax.sgd(sched)
    else:
        raise ValueError(f"unknown optimizer {config.optimizer!r}")
    if config.grad_clip_norm > 0:
        return optax.chain(optax.clip_by_global_norm(config.grad_clip_norm), opt)
    return opt


class Trainer:
    """Compiles and owns the sharded train step.

    Args:
      loss_fn: ``(params, batch) -> loss`` or, with ``has_model_state``,
        ``(params, model_state, batch) -> (loss, new_model_state)``.
      params_axes: logical-axes pytree matching params (models.*.logical_axes).
      mesh: the job mesh (runtime.mesh_from_context / build_mesh).
      model_state_axes: logical-axes pytree for model_state when stateful.
      batch_axes: logical axes for each batch leaf dim; default shards dim 0
        along (data, fsdp) — a per-leaf dict is accepted for ragged batches.
    """

    def __init__(
        self,
        loss_fn: Callable,
        params_axes: Any,
        mesh: Mesh,
        config: TrainerConfig = TrainerConfig(),
        *,
        has_model_state: bool = False,
        model_state_axes: Any = None,
        rules: Optional[Rules] = None,
        donate: bool = True,
    ):
        self.config = config
        self.mesh = mesh
        self.rules = rules
        self.has_model_state = has_model_state
        self.tx = _optimizer(config)
        if config.remat:
            loss_fn = jax.checkpoint(loss_fn)
        self._loss_fn = loss_fn
        self._params_axes = params_axes
        self._model_state_axes = model_state_axes if has_model_state else {}
        self._step_fn = None
        self._multi_fns = None  # n → compiled n-step scan (multi_step)
        self._donate = donate
        self._opt_state_sharding_template = None  # set by init_state

    # -- shardings ---------------------------------------------------------

    def _sharding_of(self, axes_tree):
        return jax.tree.map(
            lambda axes: NamedSharding(
                self.mesh,
                mesh_filtered_spec(logical_spec(axes, self.rules), self.mesh),
            ),
            axes_tree,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    def params_sharding(self):
        return self._sharding_of(self._params_axes)

    def model_state_sharding(self):
        return self._sharding_of(self._model_state_axes)

    def batch_sharding(self, batch):
        spec = mesh_filtered_spec(logical_spec(["batch"], self.rules), self.mesh)
        return jax.tree.map(lambda _: NamedSharding(self.mesh, spec), batch)

    def state_sharding(self) -> "TrainState":
        """Sharding pytree for TrainState (valid after init_state)."""
        return TrainState(
            step=NamedSharding(self.mesh, PartitionSpec()),
            params=self.params_sharding(),
            opt_state=self._opt_state_sharding_template,
            model_state=self.model_state_sharding()
            if self.has_model_state
            else {},
        )

    # -- lifecycle ---------------------------------------------------------

    def init_state(self, params, model_state: Any = None) -> TrainState:
        """Build TrainState with every array placed per the mesh layout."""
        p_sh = self.params_sharding()
        params = jax.tree.map(jax.device_put, params, p_sh)
        opt_state = jax.jit(
            self.tx.init,
            out_shardings=self._opt_sharding_for(params, p_sh),
        )(params)
        self._opt_state_sharding_template = jax.tree.map(
            lambda x: x.sharding, opt_state
        )
        if self.has_model_state:
            model_state = jax.tree.map(
                jax.device_put, model_state, self.model_state_sharding()
            )
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            model_state=model_state if self.has_model_state else {},
        )

    def _opt_sharding_for(self, params, p_sh):
        """Optimizer state sharding: moments follow params, scalars
        replicate. Matched by key *path* — optimizer moments live at paths
        whose suffix is the param's own path (e.g. chain_state[1].mu.dense1.w
        ends in dense1.w), so each moment inherits exactly its param's
        layout. Shape-based matching would collide for same-shape params
        with different shardings (llama wq vs wo)."""
        from jax.tree_util import tree_flatten_with_path

        shapes = jax.eval_shape(self.tx.init, params)
        p_flat, _ = tree_flatten_with_path(params)
        psh_flat = jax.tree.leaves(
            p_sh, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        by_path = {}
        for (path, leaf), sh in zip(p_flat, psh_flat):
            by_path[tuple(str(k) for k in path)] = (leaf.shape, sh)
        replicated = NamedSharding(self.mesh, PartitionSpec())

        def pick(path, leaf):
            keys = tuple(str(k) for k in path)
            for start in range(len(keys)):
                hit = by_path.get(keys[start:])
                if hit is not None and hit[0] == leaf.shape:
                    return hit[1]
            return replicated

        o_flat, o_def = tree_flatten_with_path(shapes)
        return jax.tree.unflatten(o_def, [pick(p, l) for p, l in o_flat])

    # -- the step ----------------------------------------------------------

    def _bare_step(self, state: TrainState, batch):
        """The un-jitted step body (shared by train_step and multi_step)."""
        if self.has_model_state:
            (loss, new_ms), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True
            )(state.params, state.model_state, batch)
        else:
            loss, grads = jax.value_and_grad(self._loss_fn)(
                state.params, batch
            )
            new_ms = state.model_state
        updates, new_opt = self.tx.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss}
        if self.config.grad_clip_norm > 0:
            # free when clipping: XLA CSEs this with the clip's norm.
            # When not clipping it would be an extra full pass over the
            # gradients, so the metric is only emitted alongside a clip.
            metrics["grad_norm"] = optax.global_norm(grads)
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                model_state=new_ms,
            ),
            metrics,
        )

    def _jit_wrap(self, fn, batch_example):
        """jit a (state, batch) -> (state, metrics) function with the
        trainer's shardings + donation (shared by train_step/multi_step so
        the two paths can never drift)."""
        state_sh = self.state_sharding()
        metrics_sh = {"loss": NamedSharding(self.mesh, PartitionSpec())}
        if self.config.grad_clip_norm > 0:
            metrics_sh["grad_norm"] = NamedSharding(self.mesh, PartitionSpec())
        return jax.jit(
            fn,
            in_shardings=(state_sh, self.batch_sharding(batch_example)),
            out_shardings=(state_sh, metrics_sh),
            donate_argnums=(0,) if self._donate else (),
        )

    def _build_step(self, batch_example):
        return self._jit_wrap(self._bare_step, batch_example)

    def train_step(self, state: TrainState, batch):
        if self._step_fn is None:
            self._step_fn = self._build_step(batch)
        return self._step_fn(state, batch)

    def multi_step(self, state: TrainState, batch, n: int):
        """Run ``n`` steps on one batch inside a single dispatch
        (lax.scan over the step; ≙ tf_cnn_benchmarks' steps-per-session-run).
        Per-dispatch host work — pytree flatten of hundreds of param leaves,
        argument donation bookkeeping — is real wall time at small step
        latencies (~5 ms/step on ResNet-101 v5e, measured); amortizing it
        across n steps removes that gap. Returns (state, last metrics).
        Intended for benchmarking/synthetic batches: every step consumes the
        SAME batch (a production loop feeds fresh data per step)."""
        if self._multi_fns is None:
            self._multi_fns = {}
        fn = self._multi_fns.get(n)
        if fn is None:

            def run(state, batch):
                def body(s, _):
                    s, m = self._bare_step(s, batch)
                    return s, m

                state, ms = jax.lax.scan(body, state, None, length=n)
                return state, jax.tree.map(lambda x: x[-1], ms)

            fn = self._jit_wrap(run, batch)
            self._multi_fns[n] = fn
        return fn(state, batch)

    def compile(self, state: TrainState, batch):
        """AOT-compile the step (returns the lowered+compiled executable;
        also caches it as the active step fn)."""
        if self._step_fn is None:
            self._step_fn = self._build_step(batch)
        return self._step_fn.lower(state, batch).compile()
