"""Checkpoint / resume (orbax-backed).

The reference has NO checkpointing — SURVEY.md §5.4: job-level "resume" is
only launcher-pod retry, and elastic Horovod recovers from in-memory state.
On TPU, preemption is routine and XLA can't re-form a ring in place
(membership change ⇒ recompile), so durable checkpoints are the recovery
primitive (SURVEY.md §7 phase 7): scale events save → re-mesh → restore.

Restore is *reshard-on-load*: the target shardings come from the new mesh,
so a checkpoint written on 16 hosts restores cleanly onto 8 or 32 — this is
exactly the elastic-resume path the controller's scale-up/down drives."""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


class CheckpointManager:
    """Thin wrapper over orbax's CheckpointManager pinned to this
    framework's TrainState layout and elastic-resume semantics."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1000,
        async_save: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                # async commit (ISSUE 16): save() returns once the device
                # arrays are snapshotted host-side; serialization to disk
                # overlaps the NEXT steps on orbax's background thread.
                # The step loop then charges only that blocking snapshot
                # slice to its `ckpt` bucket — the commit costs goodput
                # nothing. Durability is unchanged WHERE IT MATTERS: the
                # sanctioned seams (SIGTERM force-checkpoint, terminal
                # exit, pre-restore) call wait() to fence the commit.
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if the step hits the interval (or force). Multi-host safe:
        every process must call this (orbax coordinates the barrier).
        With ``async_save`` (the default) this returns after the blocking
        device→host snapshot; the disk commit overlaps later steps and is
        fenced by :meth:`wait`."""
        saved = self.manager.save(
            step, args=self._ocp.args.StandardSave(state), force=force
        )
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, state_template: Any, *, step: Optional[int] = None) -> Any:
        """Restore into the layout of ``state_template`` (an abstract or
        concrete TrainState whose shardings describe the *current* mesh —
        resharding across gang sizes happens here)."""
        # pre-restore fence (a sanctioned wait seam, oplint CKP001): an
        # in-flight async commit of the step being restored must finish
        # before its files are read back
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if hasattr(x, "sharding")
            else x,
            state_template,
        )
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )

    def wait(self) -> None:
        """Block until any async save has committed."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()
