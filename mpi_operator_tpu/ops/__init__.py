"""Training ops: distributed trainer, input pipelines, checkpointing.

≙ the glue the reference delegates to Horovod + the user script:
``hvd.DistributedOptimizer`` (gradient allreduce), ``hvd.broadcast_global_
variables`` (initial sync), tf.data input pipelines, and — absent from the
reference entirely (SURVEY.md §5.4) — checkpoint/resume, which TPU preemption
makes mandatory here.

TPU-native: the trainer compiles ONE global-view jit train step whose batch
is sharded over (data, fsdp) and whose params follow the model's logical
axes; XLA inserts the gradient reductions (there is no explicit allreduce to
call — the psum is implied by the sharding, which is the whole point of the
pjit programming model)."""

from mpi_operator_tpu.ops.trainer import Trainer, TrainerConfig, TrainState
from mpi_operator_tpu.ops.data import synthetic_imagenet, synthetic_tokens, prefetch
from mpi_operator_tpu.ops.checkpoint import CheckpointManager
from mpi_operator_tpu.ops.elastic import (
    EXIT_RESTART,
    ElasticConfig,
    ElasticResult,
    run_elastic,
)

__all__ = [
    "Trainer",
    "TrainerConfig",
    "TrainState",
    "synthetic_imagenet",
    "synthetic_tokens",
    "prefetch",
    "CheckpointManager",
    "EXIT_RESTART",
    "ElasticConfig",
    "ElasticResult",
    "run_elastic",
]
