"""Input pipelines: synthetic datasets + device prefetch.

≙ the reference benchmark's ``--data_name=imagenet`` *synthetic* mode
(tf_cnn_benchmarks generates random images when no data_dir is given —
that's what produced the 154.2 img/s baseline, /root/reference/README.md:166-199)
and Horovod's sharded tf.data feeds.

TPU-native: batches are built host-locally and assembled into global arrays
(each host owns its (data, fsdp) shard — jax.make_array_from_process_local_data),
and :func:`prefetch` keeps a small queue of device-resident batches so the
infeed overlaps the train step (the double-buffering SURVEY.md §7 flags as a
prerequisite for ≥50% MFU on conv nets)."""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from mpi_operator_tpu.parallel.sharding import logical_spec, mesh_filtered_spec


def _batch_sharding(mesh: Mesh, rules=None) -> NamedSharding:
    return NamedSharding(
        mesh, mesh_filtered_spec(logical_spec(["batch"], rules), mesh)
    )


def make_global_batch(mesh: Mesh, host_local: Dict[str, np.ndarray], rules=None):
    """Assemble per-host numpy arrays into global sharded jax.Arrays.

    Single-process (tests, one-host slices): a plain device_put with the
    batch sharding. Multi-host: each process contributes its local shard."""
    sh = _batch_sharding(mesh, rules)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sh) for k, v in host_local.items()}
    return {
        k: jax.make_array_from_process_local_data(sh, v)
        for k, v in host_local.items()
    }


def synthetic_imagenet(
    *,
    global_batch: int,
    image_size: int = 224,
    num_classes: int = 1000,
    seed: int = 0,
    dtype: str = "float32",
) -> Iterator[Dict[str, np.ndarray]]:
    """Host-local synthetic ImageNet stream (the baseline workload's data).

    Yields this host's share of each global batch. Images are fixed random
    tensors re-used every step (matching tf_cnn_benchmarks' synthetic data,
    which measures compute, not IO).

    ``dtype="uint8"`` yields raw byte images (what a real decode loop hands
    over): 4x fewer bytes across PCIe per batch, with the cast/normalize
    moved onto the device via :func:`imagenet_normalize` — the on-device
    transform placement half of the ISSUE 16 input-overlap work."""
    n_proc = jax.process_count()
    local = global_batch // n_proc
    rng = np.random.default_rng(seed + jax.process_index())
    shape = (local, image_size, image_size, 3)
    if dtype == "uint8":
        images = rng.integers(0, 256, shape, dtype=np.uint8)
    else:
        images = rng.standard_normal(shape, np.float32)
    labels = rng.integers(0, num_classes, (local,)).astype(np.int32)
    while True:
        yield {"image": images, "label": labels}


def imagenet_normalize(compute_dtype=None) -> Callable[[Any], Any]:
    """Jitted on-device input transform: uint8 images → mean/std-normalized
    float (ImageNet statistics, scaled to the 0–255 byte range).

    Pair with ``synthetic_imagenet(dtype="uint8")`` under
    ``prefetch(..., device_transform=imagenet_normalize())``: the host
    ships bytes, the accelerator does the per-pixel arithmetic, and the
    work is dispatched from the prefetch thread so it overlaps the train
    step instead of widening the host-side input bubble."""
    import jax.numpy as jnp

    mean = jnp.asarray([0.485, 0.456, 0.406], jnp.float32) * 255.0
    std = jnp.asarray([0.229, 0.224, 0.225], jnp.float32) * 255.0
    dt = compute_dtype or jnp.float32

    def tf(batch):
        out = dict(batch)
        img = batch["image"].astype(jnp.float32)
        out["image"] = ((img - mean) / std).astype(dt)
        return out

    return jax.jit(tf)


def synthetic_tokens(
    *,
    global_batch: int,
    seq_len: int,
    vocab: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Host-local synthetic LM token stream (Llama workload)."""
    n_proc = jax.process_count()
    local = global_batch // n_proc
    rng = np.random.default_rng(seed + jax.process_index())
    tokens = rng.integers(0, vocab, (local, seq_len)).astype(np.int32)
    while True:
        yield {"tokens": tokens}


def prefetch(
    it: Iterator[Dict[str, np.ndarray]],
    mesh: Mesh,
    *,
    depth: int = 2,
    transform: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None,
    device_transform: Optional[Callable[[Any], Any]] = None,
) -> Iterator[Any]:
    """Device prefetch: a background thread keeps ``depth`` global batches
    resident on device so the infeed overlaps compute (double-buffered at
    depth=2). The thread only does host→device transfers; assembly order is
    preserved.

    ``transform`` runs host-side (numpy, before the transfer);
    ``device_transform`` runs AFTER the device put, on the sharded global
    batch — pass a jitted function and per-sample work (normalization,
    augmentation, dtype casts) is dispatched to the accelerator from the
    prefetch thread, overlapping the train step instead of competing with
    the host-side input path (ISSUE 16: the `input` bucket only charges
    ``next(batches)``, and dispatch-only producer work keeps it at noise).

    A consumer that abandons the generator early — elastic restart,
    exception, plain ``break`` — CLOSES it, and the close propagates to
    the producer thread through a stop flag: without it the producer
    would block forever on a full queue, pinning ``depth`` global batches
    of device memory for the life of the process (the ISSUE 16 leak)."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    done = object()
    stop = threading.Event()

    def put(item) -> bool:
        """Deliver to the consumer unless it has gone away; the timed
        retry loop is what the stop flag interrupts (a plain q.put on a
        full queue would never re-check it)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in it:
                if stop.is_set():
                    return
                if transform is not None:
                    item = transform(item)
                batch = make_global_batch(mesh, item)
                if device_transform is not None:
                    batch = device_transform(batch)
                if not put(batch):
                    return
            put(done)
        # oplint: disable=EXC001 — not swallowed: the exception VALUE rides
        # the queue to the consumer below, which re-raises it
        except BaseException as e:  # propagate to the consumer, never hang it
            put(e)

    t = threading.Thread(target=producer, name="tpujob-prefetch", daemon=True)
    t.start()
    try:
        while True:
            # oplint: disable=BLK001 — bounded by the producer's contract:
            # it ALWAYS delivers the `done` sentinel or its own exception
            # (the BaseException relay above); a timeout here would abort
            # legitimate long preprocessing stalls mid-epoch
            item = q.get()
            if item is done:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # runs on exhaustion AND on early abandonment (GeneratorExit from
        # close(), or an exception in the consumer): release the producer
        # — flag first, then drain the queue so a put() blocked on a full
        # queue frees its slot now instead of at its next timeout tick
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
