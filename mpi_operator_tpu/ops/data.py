"""Input pipelines: synthetic datasets + device prefetch.

≙ the reference benchmark's ``--data_name=imagenet`` *synthetic* mode
(tf_cnn_benchmarks generates random images when no data_dir is given —
that's what produced the 154.2 img/s baseline, /root/reference/README.md:166-199)
and Horovod's sharded tf.data feeds.

TPU-native: batches are built host-locally and assembled into global arrays
(each host owns its (data, fsdp) shard — jax.make_array_from_process_local_data),
and :func:`prefetch` keeps a small queue of device-resident batches so the
infeed overlaps the train step (the double-buffering SURVEY.md §7 flags as a
prerequisite for ≥50% MFU on conv nets)."""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from mpi_operator_tpu.parallel.sharding import logical_spec, mesh_filtered_spec


def _batch_sharding(mesh: Mesh, rules=None) -> NamedSharding:
    return NamedSharding(
        mesh, mesh_filtered_spec(logical_spec(["batch"], rules), mesh)
    )


def make_global_batch(mesh: Mesh, host_local: Dict[str, np.ndarray], rules=None):
    """Assemble per-host numpy arrays into global sharded jax.Arrays.

    Single-process (tests, one-host slices): a plain device_put with the
    batch sharding. Multi-host: each process contributes its local shard."""
    sh = _batch_sharding(mesh, rules)
    if jax.process_count() == 1:
        return {k: jax.device_put(v, sh) for k, v in host_local.items()}
    return {
        k: jax.make_array_from_process_local_data(sh, v)
        for k, v in host_local.items()
    }


def synthetic_imagenet(
    *,
    global_batch: int,
    image_size: int = 224,
    num_classes: int = 1000,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Host-local synthetic ImageNet stream (the baseline workload's data).

    Yields this host's share of each global batch. Images are fixed random
    tensors re-used every step (matching tf_cnn_benchmarks' synthetic data,
    which measures compute, not IO)."""
    n_proc = jax.process_count()
    local = global_batch // n_proc
    rng = np.random.default_rng(seed + jax.process_index())
    images = rng.standard_normal((local, image_size, image_size, 3), np.float32)
    labels = rng.integers(0, num_classes, (local,)).astype(np.int32)
    while True:
        yield {"image": images, "label": labels}


def synthetic_tokens(
    *,
    global_batch: int,
    seq_len: int,
    vocab: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Host-local synthetic LM token stream (Llama workload)."""
    n_proc = jax.process_count()
    local = global_batch // n_proc
    rng = np.random.default_rng(seed + jax.process_index())
    tokens = rng.integers(0, vocab, (local, seq_len)).astype(np.int32)
    while True:
        yield {"tokens": tokens}


def prefetch(
    it: Iterator[Dict[str, np.ndarray]],
    mesh: Mesh,
    *,
    depth: int = 2,
    transform: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None,
) -> Iterator[Any]:
    """Device prefetch: a background thread keeps ``depth`` global batches
    resident on device so the infeed overlaps compute (double-buffered at
    depth=2). The thread only does host→device transfers; assembly order is
    preserved."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    done = object()

    def producer():
        try:
            for item in it:
                if transform is not None:
                    item = transform(item)
                q.put(make_global_batch(mesh, item))
            q.put(done)
        # oplint: disable=EXC001 — not swallowed: the exception VALUE rides
        # the queue to the consumer below, which re-raises it
        except BaseException as e:  # propagate to the consumer, never hang it
            q.put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        # oplint: disable=BLK001 — bounded by the producer's contract: it
        # ALWAYS delivers the `done` sentinel or its own exception (the
        # BaseException relay above); a timeout here would abort legitimate
        # long preprocessing stalls mid-epoch
        item = q.get()
        if item is done:
            return
        if isinstance(item, BaseException):
            raise item
        yield item
