"""Elastic training loop: membership changes → checkpoint → re-mesh → resume.

≙ the reference's elastic-Horovod capability (SURVEY.md §3.5: controller
publishes discover_hosts.sh, horovodrun re-forms the ring in place, in-memory
state recovery) — redesigned for XLA's reality (SURVEY.md §7 "hard parts"):
a compiled program is fixed to its mesh, so membership changes cannot re-form
in place. The TPU-native protocol is restart-based:

  1. every worker trains under a jit step compiled for the current gang;
  2. a membership source (the controller-projected config file, or any
     callable) reports the *desired* world size;
  3. on change, every worker force-checkpoints and exits with
     EXIT_RESTART (EX_TEMPFAIL) — a retryable code under
     restart_policy: ExitCode;
  4. the controller re-runs the gang at the new size; workers restore from
     the checkpoint (reshard-on-load, ops/checkpoint.py) and continue at the
     saved step.

State survives via orbax instead of Horovod's in-memory rings because TPU
preemption would lose in-memory state anyway — the checkpoint path must
exist, so it IS the elasticity path.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Iterator, Optional

from mpi_operator_tpu.ops.checkpoint import CheckpointManager
from mpi_operator_tpu.ops.profiling import StepProfiler
from mpi_operator_tpu.ops.trainer import Trainer, TrainState

# EX_TEMPFAIL: the "re-run me" exit code workers use on membership change.
# Job specs pair it with restart_policy: ExitCode (the controller treats the
# exit as retryable and relaunches the gang, ≙ setRestartPolicy :1394-1400).
EXIT_RESTART = 75

ENV_CONFIG_DIR = "TPUJOB_CONFIG_DIR"
HOSTFILE_NAME = "hostfile"


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    checkpoint_dir: str = ""
    save_interval_steps: int = 100
    membership_check_every: int = 10


@dataclasses.dataclass
class ElasticResult:
    outcome: str  # "done" | "restart"
    state: Any
    last_step: int
    metrics: Optional[Dict[str, float]] = None
    start_step: int = 0  # step this incarnation resumed from (0 = fresh)

    @property
    def steps_run(self) -> int:
        """Steps executed by THIS process (excludes restored progress) —
        the denominator-matching count for throughput reporting."""
        return self.last_step - self.start_step

    @property
    def exit_code(self) -> int:
        return 0 if self.outcome == "done" else EXIT_RESTART


def declared_world_size() -> int:
    """Desired gang size per the controller: hostfile lines in the projected
    config dir (≙ discover_hosts.sh consumers; the executor/kubelet syncs
    the file when the controller rescales)."""
    cfg_dir = os.environ.get(ENV_CONFIG_DIR, "")
    path = os.path.join(cfg_dir, HOSTFILE_NAME)
    if not cfg_dir or not os.path.exists(path):
        return int(os.environ.get("TPUJOB_NUM_HOSTS", "1"))
    with open(path) as f:
        return sum(1 for line in f if line.strip())


def run_elastic(
    trainer: Trainer,
    batches: Iterator[Any],
    *,
    total_steps: int,
    config: ElasticConfig,
    init_state: Callable[[], TrainState],
    membership: Callable[[], int] = declared_world_size,
    current_world: Optional[int] = None,
) -> ElasticResult:
    """Train to total_steps or until membership changes.

    ``init_state`` builds a fresh TrainState (used only when no checkpoint
    exists); otherwise the latest checkpoint is restored INTO the current
    mesh layout. Returns "restart" (caller exits EXIT_RESTART) or "done".
    """
    import jax

    if current_world is None:
        current_world = jax.process_count()

    def agreed_membership() -> int:
        """Host 0's membership view, broadcast to the gang. Each host polls
        its own projected hostfile, and projection timing skews across
        hosts — if hosts acted on their *local* read they could diverge on
        which step to exit at, desynchronizing the collectives (the step
        loop is SPMD: every control-flow decision must be gang-uniform).
        A one-to-all broadcast runs at a synchronized point of every
        participant's loop, so the decision is uniform by construction.
        Single-process: a passthrough."""
        if jax.process_count() == 1:
            return membership()
        import numpy as np
        from jax.experimental import multihost_utils

        return int(multihost_utils.broadcast_one_to_all(np.int32(membership())))
    mgr = CheckpointManager(
        config.checkpoint_dir,
        save_interval_steps=config.save_interval_steps,
    )
    template = init_state()
    if mgr.latest_step() is not None:
        state = mgr.restore(template)
    else:
        state = template

    # Track the step host-side: int(state.step) forces a device sync on a
    # jit output, which would serialize dispatch of step N+1 behind compute
    # of step N every iteration. One sync at restore, then a local counter.
    step = start_step = int(state.step)
    metrics = None
    profiler = StepProfiler()  # no-op unless TPUJOB_PROFILE_DIR is set
    try:
        while step < total_steps:
            state, metrics = trainer.train_step(state, next(batches))
            step += 1
            profiler.observe(step)
            if step % config.save_interval_steps == 0:
                mgr.save(step, state)
            if (
                step % config.membership_check_every == 0
                and agreed_membership() != current_world
            ):
                if mgr.latest_step() != step:
                    mgr.save(step, state, force=True)
                mgr.wait()
                return ElasticResult(
                    "restart",
                    state,
                    step,
                    {k: float(v) for k, v in (metrics or {}).items()},
                    start_step=start_step,
                )
        if mgr.latest_step() != step:
            mgr.save(step, state, force=True)
        mgr.wait()
    finally:
        profiler.close()
        mgr.close()
    return ElasticResult(
        "done",
        state,
        step,
        {k: float(v) for k, v in (metrics or {}).items()},
        start_step=start_step,
    )
