"""Elastic training loop: membership changes → checkpoint → re-mesh → resume.

≙ the reference's elastic-Horovod capability (SURVEY.md §3.5: controller
publishes discover_hosts.sh, horovodrun re-forms the ring in place, in-memory
state recovery) — redesigned for XLA's reality (SURVEY.md §7 "hard parts"):
a compiled program is fixed to its mesh, so membership changes cannot re-form
in place. The TPU-native protocol is restart-based:

  1. every worker trains under a jit step compiled for the current gang;
  2. a membership source (the controller-projected config file, or any
     callable) reports the *desired* world size;
  3. on change, every worker force-checkpoints and exits with
     EXIT_RESTART (EX_TEMPFAIL) — a retryable code under
     restart_policy: ExitCode;
  4. the controller re-runs the gang at the new size; workers restore from
     the checkpoint (reshard-on-load, ops/checkpoint.py) and continue at the
     saved step.

State survives via orbax instead of Horovod's in-memory rings because TPU
preemption would lose in-memory state anyway — the checkpoint path must
exist, so it IS the elasticity path.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
from typing import Any, Callable, Dict, Iterator, Optional

from mpi_operator_tpu.ops.checkpoint import CheckpointManager
from mpi_operator_tpu.ops.profiling import ProfileRequestWatcher, StepProfiler
from mpi_operator_tpu.ops.trainer import Trainer, TrainState
from mpi_operator_tpu.runtime.stepstats import StepStatsRecorder

# EX_TEMPFAIL: the "re-run me" exit code workers use on membership change.
# Job specs pair it with restart_policy: ExitCode (the controller treats the
# exit as retryable and relaunches the gang, ≙ setRestartPolicy :1394-1400).
EXIT_RESTART = 75

ENV_CONFIG_DIR = "TPUJOB_CONFIG_DIR"
HOSTFILE_NAME = "hostfile"


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    checkpoint_dir: str = ""
    save_interval_steps: int = 100
    membership_check_every: int = 10


@dataclasses.dataclass
class ElasticResult:
    outcome: str  # "done" | "restart"
    state: Any
    last_step: int
    metrics: Optional[Dict[str, float]] = None
    start_step: int = 0  # step this incarnation resumed from (0 = fresh)

    @property
    def steps_run(self) -> int:
        """Steps executed by THIS process (excludes restored progress) —
        the denominator-matching count for throughput reporting."""
        return self.last_step - self.start_step

    @property
    def exit_code(self) -> int:
        return 0 if self.outcome == "done" else EXIT_RESTART


# preemption signal: eviction (scheduler preemption, `ctl drain`, node
# shutdown) reaches the worker as SIGTERM with a kill grace behind it
# (executor/local.py eviction_grace — ≙ terminationGracePeriodSeconds).
# The handler only sets a flag: checkpointing from inside a signal handler
# would re-enter orbax/XLA mid-step. The step loop folds the flag into its
# gang-synchronized membership check so every host force-checkpoints at
# the SAME step — a lone host checkpointing on its own signal timing would
# diverge the SPMD control flow and hang the gang's collectives.
_PREEMPTED = threading.Event()


def install_preemption_handler() -> None:
    """Route SIGTERM into the elastic loop's checkpoint-and-exit path.
    Main-thread only (signal module contract); a no-op elsewhere so
    library callers embedded in servers don't crash."""
    try:
        signal.signal(signal.SIGTERM, lambda sig, frame: _PREEMPTED.set())
    except ValueError:
        pass  # not the main thread: the host process owns signal routing


def preemption_requested() -> bool:
    return _PREEMPTED.is_set()


def declared_world_size() -> int:
    """Desired gang size per the controller: hostfile lines in the projected
    config dir (≙ discover_hosts.sh consumers; the executor/kubelet syncs
    the file when the controller rescales)."""
    cfg_dir = os.environ.get(ENV_CONFIG_DIR, "")
    path = os.path.join(cfg_dir, HOSTFILE_NAME)
    if not cfg_dir or not os.path.exists(path):
        return int(os.environ.get("TPUJOB_NUM_HOSTS", "1"))
    with open(path) as f:
        return sum(1 for line in f if line.strip())


def _final_checkpoint(mgr: CheckpointManager, stats: StepStatsRecorder,
                      step: int, state: Any) -> None:
    """THE sanctioned blocking-wait seam (oplint CKP001): the only places
    the step loop may block on a checkpoint COMMIT are the SIGTERM
    force-checkpoint (the eviction grace window is about to expire — an
    uncommitted save is a lost step) and the terminal exit (the process
    is about to vanish). Periodic saves stay async: their commit overlaps
    the next steps and the `ckpt` bucket charges only the blocking
    device→host snapshot slice, which is what keeps the goodput pager
    silent through steady-state saves."""
    with stats.phase("ckpt"):
        if mgr.latest_step() != step:
            mgr.save(step, state, force=True)
        mgr.wait()


def run_elastic(
    trainer: Trainer,
    batches: Iterator[Any],
    *,
    total_steps: int,
    config: ElasticConfig,
    init_state: Callable[[], TrainState],
    membership: Callable[[], int] = declared_world_size,
    current_world: Optional[int] = None,
) -> ElasticResult:
    """Train to total_steps or until membership changes.

    ``init_state`` builds a fresh TrainState (used only when no checkpoint
    exists); otherwise the latest checkpoint is restored INTO the current
    mesh layout. Returns "restart" (caller exits EXIT_RESTART) or "done".
    """
    import jax

    if current_world is None:
        current_world = jax.process_count()

    def agreed_gang_state() -> "tuple[int, bool]":
        """(desired world size, preemption requested) as ONE gang-uniform
        decision. Each host polls its own projected hostfile, and
        projection timing skews across hosts — if hosts acted on their
        *local* read they could diverge on which step to exit at,
        desynchronizing the collectives (the step loop is SPMD: every
        control-flow decision must be gang-uniform). Same argument for
        SIGTERM: eviction delivers it to each host on its own schedule, so
        the checkpoint-and-exit decision is an allgather-OR (any host
        signaled → the whole gang exits at this step), not a local check.
        Membership stays host 0's view (the old broadcast semantics);
        single-process is a passthrough."""
        if jax.process_count() == 1:
            return membership(), _PREEMPTED.is_set()
        import numpy as np
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            np.array([membership(), int(_PREEMPTED.is_set())],
                     dtype=np.int32)
        )
        return int(gathered[0][0]), bool(gathered[:, 1].any())

    # clear-then-install: a fresh incarnation cannot still be preempted by
    # a signal delivered to a PREVIOUS run in this process (the flag would
    # otherwise force-restart every later run at its first sync point). A
    # SIGTERM before the install kills the process outright (default
    # disposition), so nothing meaningful can race the clear.
    _PREEMPTED.clear()
    install_preemption_handler()
    mgr = CheckpointManager(
        config.checkpoint_dir,
        save_interval_steps=config.save_interval_steps,
    )
    template = init_state()
    if mgr.latest_step() is not None:
        state = mgr.restore(template)
    else:
        state = template

    # Track the step host-side: int(state.step) forces a device sync on a
    # jit output, which would serialize dispatch of step N+1 behind compute
    # of step N every iteration. One sync at restore, then a local counter.
    step = start_step = int(state.step)
    metrics = None
    profiler = StepProfiler()  # no-op unless TPUJOB_PROFILE_DIR is set
    # the workload telemetry plane (ISSUE 15): every wall-second of every
    # step classifies into an attributed bucket — input wait, compute (the
    # first one lands in `compile`), membership sync, checkpoint save —
    # flushed to $TPUJOB_STEPSTATS_FILE for the executor to mirror into
    # pod.status.train_stats. Two perf_counter calls per phase: the
    # goodput bench pins the per-step cost at <=2% of step p50.
    stats = StepStatsRecorder.from_env()
    # operator-triggered profiling: `ctl profile` stamps the annotation,
    # the controller projects it into the same config dir the membership
    # check polls; captures land under the job's artifact dir
    prof_watch = ProfileRequestWatcher(
        stats,
        out_root=(os.path.join(config.checkpoint_dir, "profiles")
                  if config.checkpoint_dir else None),
    )
    try:
        while step < total_steps:
            with stats.phase("input"):
                batch = next(batches)
            with stats.phase("compute"):
                state, metrics = trainer.train_step(state, batch)
            step += 1
            profiler.observe(step)
            prof_watch.observe(step)
            stats.step_done(step)
            if step % config.save_interval_steps == 0:
                # async save: returns after the blocking device→host
                # snapshot; the disk commit overlaps the next steps, so
                # this phase charges only the blocking slice (the old
                # synchronous save stalled the whole gang here for the
                # full serialize+fsync — the periodic `ckpt` spike the
                # goodput pager used to see)
                with stats.phase("ckpt"):
                    mgr.save(step, state)
            if step % config.membership_check_every == 0:
                with stats.phase("sync"):
                    want, preempted = agreed_gang_state()
                prof_watch.poll(step)
                if preempted or want != current_world:
                    # force-checkpoint BEFORE exiting: for preemption this
                    # runs inside the executor's eviction grace window, so
                    # the next incarnation resumes from this step instead
                    # of the last periodic save
                    _final_checkpoint(mgr, stats, step, state)
                    return ElasticResult(
                        "restart",
                        state,
                        step,
                        {k: float(v) for k, v in (metrics or {}).items()},
                        start_step=start_step,
                    )
        _final_checkpoint(mgr, stats, step, state)
    finally:
        prof_watch.close()
        stats.close()
        profiler.close()
        mgr.close()
    return ElasticResult(
        "done",
        state,
        step,
        {k: float(v) for k, v in (metrics or {}).items()},
        start_step=start_step,
    )
