"""XLA profiler hooks for training loops.

≙ SURVEY.md §5.1's TPU-build obligation: the reference punts workload
profiling to the roadmap (Horovod Timeline, /root/reference/ROADMAP.md:14);
here every worker can capture an XLA trace of a step window with zero code
changes — the controller passes container env through, so setting

    TPUJOB_PROFILE_DIR=/tmp/trace        (per-host subdir appended)
    TPUJOB_PROFILE_START=10              (first step to trace, default 10)
    TPUJOB_PROFILE_STEPS=5               (how many steps, default 5)

on a job's worker template makes each host write an xplane trace readable
with xprof/tensorboard (see PERF.md for the analysis recipe).

Since the workload telemetry plane (ISSUE 15) there is also the
OPERATOR-TRIGGERED path: ``ctl profile <job> --steps N`` stamps the
``tpujob.dev/profile-request`` annotation, the controller projects it
into the job ConfigMap's ``profile`` key (the same projected-file channel
the elastic membership check already polls), and each worker's
:class:`ProfileRequestWatcher` captures a ``jax.profiler`` trace for N
steps into the job's artifact dir, acking progress through its
train_stats ``profile`` entry (``ctl profile --status/--fetch`` read the
acks back) — attaching a profiler to a live gang without restarting it.
Capture is host-local tracing with no effect on SPMD control flow, so
each host may start on its own request-file timing.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Any, Dict, Optional

log = logging.getLogger("tpujob.profiling")

ENV_DIR = "TPUJOB_PROFILE_DIR"
ENV_START = "TPUJOB_PROFILE_START"
ENV_STEPS = "TPUJOB_PROFILE_STEPS"

# the ConfigMap key the controller projects the profile-request
# annotation into (a file under $TPUJOB_CONFIG_DIR, like the hostfile)
PROFILE_REQUEST_FILE = "profile"


class StepProfiler:
    """Drive from a training loop: call observe(step) once per step; the
    trace starts/stops itself around the configured window. No-op (and
    import-free) when TPUJOB_PROFILE_DIR is unset."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory if directory is not None else os.environ.get(ENV_DIR, "")
        self.start_step = int(os.environ.get(ENV_START, "10") or "10")
        self.num_steps = max(1, int(os.environ.get(ENV_STEPS, "5") or "5"))
        self._active = False
        self._done = False

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def _trace_dir(self) -> str:
        import jax

        return os.path.join(self.directory, f"host{jax.process_index()}")

    def observe(self, step: int) -> None:
        if not self.enabled or self._done:
            return
        import jax

        if not self._active and self.start_step <= step < self.start_step + self.num_steps:
            jax.profiler.start_trace(self._trace_dir())
            self._active = True
        elif self._active and step >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True


class ProfileRequestWatcher:
    """The operator-triggered profiling hook: polls the controller-
    projected request file at the membership-check cadence, captures a
    ``jax.profiler`` trace for the requested step window, and acks
    progress through the step-stats recorder (→ pod status → `ctl
    profile --status`).

    Drive from a training loop::

        watcher = ProfileRequestWatcher(stats, out_root=...)
        ...
        watcher.observe(step)           # every step (no-op unless active)
        if step % check_every == 0:
            watcher.poll(step)          # re-read the projected request

    ``start_trace``/``stop_trace`` are injectable so tests never need a
    live jax; the defaults import jax lazily on first capture.
    """

    def __init__(self, stats=None, *, config_dir: Optional[str] = None,
                 out_root: Optional[str] = None,
                 host_index: Optional[int] = None,
                 start_trace=None, stop_trace=None):
        self.stats = stats  # StepStatsRecorder (acks ride its blob); opt
        self.config_dir = (
            config_dir if config_dir is not None
            else os.environ.get("TPUJOB_CONFIG_DIR", "")
        )
        self.out_root = out_root or os.path.join(
            tempfile.gettempdir(), "tpujob-profiles",
            os.environ.get("TPUJOB_NAMESPACE", "default")
            + "-" + os.environ.get("TPUJOB_NAME", "job"),
        )
        self._host_index = host_index
        self._start = start_trace or self._jax_start
        self._stop = stop_trace or self._jax_stop
        self._handled: Optional[str] = None  # last request id acted on
        self._active: Optional[Dict[str, Any]] = None  # {id, until, dir}

    # -- jax backends (lazy: the watcher must import clean without jax) ------

    def _host(self) -> int:
        if self._host_index is not None:
            return self._host_index
        import jax

        return jax.process_index()

    def _jax_start(self, directory: str) -> None:
        import jax

        jax.profiler.start_trace(directory)

    def _jax_stop(self) -> None:
        import jax

        jax.profiler.stop_trace()

    # -- the request channel -------------------------------------------------

    def _read_request(self) -> Optional[Dict[str, Any]]:
        if not self.config_dir:
            return None
        path = os.path.join(self.config_dir, PROFILE_REQUEST_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                raw = f.read().strip()
        except OSError:
            return None
        if not raw:
            return None
        try:
            req = json.loads(raw)
        except ValueError:
            log.warning("malformed profile request ignored: %.128s", raw)
            return None
        if not isinstance(req, dict) or not req.get("id"):
            return None
        return req

    def poll(self, step: int) -> None:
        """Check the projected request file (membership-check cadence —
        one stat+read per check, never per step)."""
        if self._active is not None:
            return
        req = self._read_request()
        if req is None or str(req["id"]) == self._handled:
            # compare NORMALIZED: a hand-stamped numeric id must not read
            # as forever-new and restart the capture on every poll
            return
        self._handled = str(req["id"])
        try:
            steps = max(1, int(req.get("steps", 5)))
        except (TypeError, ValueError):
            steps = 5
        try:
            host = self._host()
        except Exception as e:
            # the lazy jax import / process_index() can itself fail (no
            # profiler build, half-initialized jax.distributed) — the
            # module contract says a broken backend must not kill the
            # training loop, and since the annotation is never cleared a
            # propagated exception here would crash-loop every relaunch
            log.warning("profile capture failed: host index "
                        "unavailable: %s", e)
            if self.stats is not None:
                self.stats.set_profile(
                    self._handled, "failed",
                    os.path.join(self.out_root, self._handled))
            return
        directory = os.path.join(self.out_root, self._handled,
                                 f"host{host}")
        try:
            already = os.path.isdir(directory) and os.listdir(directory)
        except OSError:
            already = False
        if already:
            # the annotation is never cleared and _handled is
            # per-process: a RELAUNCHED worker (preemption, rescale,
            # migration — routine for elastic gangs) re-reads the old
            # request with fresh state. The artifact dir lives on the
            # SHARED checkpoint volume, so a non-empty host dir IS the
            # durable 'this id already captured here' marker — ack done,
            # never overwrite a fetched trace or re-pay the overhead.
            log.info("profile %s: already captured (%s); skipping",
                     self._handled, directory)
            if self.stats is not None:
                self.stats.set_profile(self._handled, "done", directory)
            return
        try:
            os.makedirs(directory, exist_ok=True)
            self._start(directory)
        except Exception as e:
            # a broken profiler backend must not kill the training loop;
            # the failure is the ack the requester sees
            log.warning("profile capture failed to start: %s", e)
            if self.stats is not None:
                self.stats.set_profile(self._handled, "failed", directory)
            return
        self._active = {"id": self._handled, "until": step + steps,
                        "dir": directory}
        log.info("profile %s: capturing %d steps into %s",
                 self._handled, steps, directory)
        if self.stats is not None:
            self.stats.set_profile(self._handled, "capturing", directory)

    def observe(self, step: int) -> None:
        """Per-step hook: stops the capture once the window elapsed."""
        act = self._active
        if act is None or step < act["until"]:
            return
        self._finish("done")

    def _finish(self, state: str) -> None:
        act, self._active = self._active, None
        if act is None:
            return
        try:
            self._stop()
        except Exception as e:
            log.warning("profile trace stop failed: %s", e)
            state = "failed"
        # the requester polls pod status for exactly this transition
        if self.stats is not None:
            self.stats.set_profile(act["id"], state, act["dir"])
        log.info("profile %s: %s (%s)", act["id"], state, act["dir"])

    def close(self) -> None:
        """End-of-run cleanup: an in-flight capture stops and acks (a
        gang restarting mid-capture leaves a truncated-but-valid trace,
        not a wedged profiler)."""
        if self._active is not None:
            self._finish("done")
