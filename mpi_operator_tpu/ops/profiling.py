"""XLA profiler hook for training loops.

≙ SURVEY.md §5.1's TPU-build obligation: the reference punts workload
profiling to the roadmap (Horovod Timeline, /root/reference/ROADMAP.md:14);
here every worker can capture an XLA trace of a step window with zero code
changes — the controller passes container env through, so setting

    TPUJOB_PROFILE_DIR=/tmp/trace        (per-host subdir appended)
    TPUJOB_PROFILE_START=10              (first step to trace, default 10)
    TPUJOB_PROFILE_STEPS=5               (how many steps, default 5)

on a job's worker template makes each host write an xplane trace readable
with xprof/tensorboard (see PERF.md for the analysis recipe).
"""

from __future__ import annotations

import os
from typing import Optional

ENV_DIR = "TPUJOB_PROFILE_DIR"
ENV_START = "TPUJOB_PROFILE_START"
ENV_STEPS = "TPUJOB_PROFILE_STEPS"


class StepProfiler:
    """Drive from a training loop: call observe(step) once per step; the
    trace starts/stops itself around the configured window. No-op (and
    import-free) when TPUJOB_PROFILE_DIR is unset."""

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory if directory is not None else os.environ.get(ENV_DIR, "")
        self.start_step = int(os.environ.get(ENV_START, "10") or "10")
        self.num_steps = max(1, int(os.environ.get(ENV_STEPS, "5") or "5"))
        self._active = False
        self._done = False

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def _trace_dir(self) -> str:
        import jax

        return os.path.join(self.directory, f"host{jax.process_index()}")

    def observe(self, step: int) -> None:
        if not self.enabled or self._done:
            return
        import jax

        if not self._active and self.start_step <= step < self.start_step + self.num_steps:
            jax.profiler.start_trace(self._trace_dir())
            self._active = True
        elif self._active and step >= self.start_step + self.num_steps:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True
