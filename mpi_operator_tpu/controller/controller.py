"""TPUJob controller: level-triggered reconciliation.

≙ /root/reference/v2/pkg/controller/mpi_job_controller.go (1531 LoC, the core
of the reference operator). The reconcile contract is preserved:

  syncHandler (:443-608): lister get → deepcopy → default → validate →
  finished-cleanup → dependents (service, config, gang, workers) → status
  mirror — all idempotent getOrCreate with ownership adoption checks
  (:625-631, :730-734), driven by a rate-limited workqueue fed by watches on
  the job and every owned kind (handleObject :300-339).

TPU-first redesign (SURVEY.md §7.3-4):
- **Launcher-less**: no launcher pod, no SSH secret, no kubectl-delivery.
  Worker 0 is the coordinator; its exit status plays the role the launcher's
  does in updateMPIJobStatus (:921-996).
- **Bootstrap = env injection**: instead of hostfiles + OMPI_MCA_* env
  (:176-200) the controller injects TPUJOB_* rendezvous env (coordinator
  address, host id/count, slice geometry) consumed by
  runtime/bootstrap.py — the jax.distributed.initialize contract.
- **Gang = slice placement**: a PodGroup with min_member == workers (no +1 —
  there is no launcher) plus ICI-topology host coordinates stamped on every
  pod (controller/placement.py).
- **RunPolicy is actually implemented** (suspend, backoffLimit,
  activeDeadlineSeconds, ttlSecondsAfterFinished) — the reference declares it
  but its v1/v2 controllers never read it (SURVEY.md §2.2, §5.3).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional

from mpi_operator_tpu.api import conditions as cond
from mpi_operator_tpu.api.defaults import set_defaults
from mpi_operator_tpu.api.types import (
    CleanPodPolicy,
    ConditionType,
    Container,
    ObjectMeta,
    OwnerReference,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    TPUJob,
)
from mpi_operator_tpu.api.validation import validate_tpujob
from mpi_operator_tpu.controller.placement import (
    PlacementError,
    SlicePlacement,
    place_workers,
)
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.events import NORMAL, WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_PROFILE_REQUEST,
    REASON_MAINTENANCE,
    ConfigMap,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodPhase,
    PodSpec,
    Service,
    ServiceSpec,
)
from mpi_operator_tpu.machinery.cache import InformerCache
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    WatchEvent,
    diff_merge_patch,
)
from mpi_operator_tpu.machinery.workqueue import (
    RateLimitingQueue,
    ShardedRateLimitingQueue,
)
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.controller")

# Pod labels (≙ the group/job/replica labels of newWorker :1246-1260)
LABEL_JOB_NAME = "tpujob.dev/job-name"
LABEL_ROLE = "tpujob.dev/job-role"
LABEL_REPLICA_INDEX = "tpujob.dev/replica-index"
# restart generation the pod was launched for (status.restart_count at
# creation): the observable that lets the chaos invariant checker prove
# "at most one gang generation launching at a time" from the event trail
# alone (tests/invariants.py) — without it, two overlapping generations
# are indistinguishable from one
LABEL_GENERATION = "tpujob.dev/generation"
ROLE_WORKER = "worker"

# Rendezvous env contract (≙ the OMPI/Intel env of :176-200; consumed by
# runtime/bootstrap.py the way mpirun consumes the hostfile env).
ENV_JOB_NAME = "TPUJOB_NAME"
ENV_NAMESPACE = "TPUJOB_NAMESPACE"
ENV_COORDINATOR = "TPUJOB_COORDINATOR_ADDRESS"
ENV_NUM_HOSTS = "TPUJOB_NUM_HOSTS"
ENV_HOST_ID = "TPUJOB_HOST_ID"
ENV_CHIPS_PER_HOST = "TPUJOB_CHIPS_PER_HOST"
ENV_ACCELERATOR = "TPUJOB_ACCELERATOR"
ENV_TOPOLOGY = "TPUJOB_TOPOLOGY"
ENV_HOST_MESH = "TPUJOB_HOST_MESH"
ENV_HOST_COORD = "TPUJOB_HOST_COORD"
ENV_SLICE_ID = "TPUJOB_SLICE_ID"
ENV_NUM_SLICES = "TPUJOB_NUM_SLICES"
# spec.compile_cache projection ("1"/"0", ISSUE 16): the EXECUTOR reads
# this gate and, when on, injects its node-local persistent-cache dir as
# $TPUJOB_COMPILE_CACHE_DIR (runtime/compile_cache.py owns that name —
# same split as the stepstats file: controller knows policy, executor
# knows node paths)
ENV_COMPILE_CACHE = "TPUJOB_COMPILE_CACHE"

DEFAULT_COORDINATOR_PORT = 8476

# Deliberately duplicated from ops/elastic.py (EXIT_RESTART): the controller
# must not import the jax-heavy training stack. tests/test_controller.py
# asserts the two stay identical.
EXIT_RESTART = 75

# ConfigMap keys (≙ hostfile / discover_hosts.sh, :1088-1138)
CONFIG_HOSTFILE = "hostfile"
CONFIG_DISCOVER_HOSTS = "discover_hosts.sh"
CONFIG_COORDINATOR = "coordinator"
# the on-demand profiling channel (ISSUE 15): the tpujob.dev/profile-
# request annotation, projected verbatim into the config dir the elastic
# membership check already polls — stamping the annotation reaches every
# worker through the SAME file-sync path a rescale does
CONFIG_PROFILE = "profile"

EVENT_VALIDATION_ERROR = "ValidationError"
EVENT_PLACEMENT_ERROR = "PlacementError"


@dataclass
class ControllerOptions:
    """≙ the operator flags (v2/cmd/mpi-operator/app/options/options.go:46-74)."""

    namespace: Optional[str] = None  # None = cluster-scoped
    threadiness: int = 2
    # workqueue shard count (the 10k-job dispatch bottleneck fix): None =
    # one shard per worker thread (dispatch parallelism tracks the pool),
    # 1 = the classic single RateLimitingQueue, N = explicit. Same key
    # never processed concurrently regardless of the shape.
    queue_shards: Optional[int] = None
    coordinator_port: int = DEFAULT_COORDINATOR_PORT
    gang_scheduling: bool = True
    # Event TTL sweep (the controller's housekeeping pass): Events older
    # than this are pruned — kube's apiserver does the same (default 1h),
    # and without it the append-only audit stream grows the store without
    # bound. None disables (embedded/test controllers keep full trails);
    # the operator CLI turns it on by default.
    event_ttl: Optional[float] = None
    event_gc_interval: float = 60.0


class TPUJobController:
    """Level-triggered reconciler over an ObjectStore.

    ≙ MPIJobController (mpi_job_controller.go:208-245). ``_write_status`` is
    the injectable status-update hook the reference exposes for tests
    (updateStatusHandler field :243-244).
    """

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        options: Optional[ControllerOptions] = None,
        cache: Optional["InformerCache"] = None,
    ):
        self.store = store
        # informer-style read path (≙ the listers syncHandler reads instead
        # of the apiserver): when a started InformerCache is supplied, every
        # read goes to it — writes still hit the store, and the cache
        # observes them through its watch, exactly like client-go. Without
        # one, reads fall through to the store (tests, runlocal).
        self.cache = cache
        self.read = cache if cache is not None else store
        self.options = options or ControllerOptions()
        self.recorder = recorder or EventRecorder(store)
        shards = self.options.queue_shards
        if shards is None:
            shards = max(1, self.options.threadiness)
        self.queue = (
            ShardedRateLimitingQueue(shards) if shards > 1
            else RateLimitingQueue()
        )
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._watch_q = None
        # (job uid, restart generation) pairs already warned about a
        # non-retryable drain-wait — the event fires once per generation
        self._drain_noted: set = set()
        # injectable, ≙ updateStatusHandler (:243-244)
        self._write_status = self._default_write_status
        # in-flight port reservations: two reconcile threads assigning ports
        # concurrently must not both pick the same one before either status
        # persists (cleared when the job disappears)
        self._port_lock = threading.Lock()
        self._ports_inflight: Dict[str, int] = {}
        # TTL-cached TPUJob snapshot for port probing (see
        # _assign_coordinator_port): (jobs, taken_at_monotonic) or None
        self._ports_snapshot = None
        # job key → span context of the latest watch write that enqueued
        # it: the reconcile span's causal parent ("why did this reconcile
        # run"). Last-writer-wins per key matches the workqueue's own
        # coalescing; popped at reconcile start, bounded by live keys.
        self._trace_lock = threading.Lock()
        self._trace_links: Dict[str, object] = {}
        # job uid → trace id this controller stamped (bounded memo; see
        # _ensure_trace_id)
        self._stamped_traces: Dict[str, str] = {}

    # ------------------------------------------------------------------
    # run loop (≙ Run + runWorker + processNextWorkItem :347-438)
    # ------------------------------------------------------------------

    def run(self) -> None:
        """Start the watch pump + worker threads. Non-blocking; stop()."""
        if self.cache is not None:
            # the workqueue is fed FROM the informer (≙ the event handlers
            # client-go registers on the SharedInformer, :300-339): handler
            # callbacks fire only after the cache applied the event, so a
            # worker dequeuing the key is guaranteed a cache at-or-after
            # that event. A separate direct store watch could enqueue a
            # fresh job BEFORE the cache observed it — the worker's cache
            # miss would read as "deleted", return success, and nothing
            # would ever re-enqueue it.
            self.cache.add_event_handler(
                lambda etype, obj: self._pump_obj(obj)
            )
        else:
            self._watch_q = self.store.watch(None)
            pump = threading.Thread(
                target=self._pump, name="tpujob-watch-pump", daemon=True
            )
            pump.start()
            self._threads.append(pump)
        for i in range(self.options.threadiness):
            t = threading.Thread(
                target=self._run_worker, args=(i,),
                name=f"tpujob-worker-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        # prime: enqueue all existing jobs (informer initial list) — from
        # the cache once it has synced (≙ WaitForCacheSync before workers)
        prime = threading.Thread(target=self._prime, name="tpujob-prime", daemon=True)
        prime.start()
        self._threads.append(prime)
        if self.options.event_ttl is not None:
            hk = threading.Thread(
                target=self._housekeeping_loop, name="tpujob-housekeeping",
                daemon=True,
            )
            hk.start()
            self._threads.append(hk)

    def _wait_cache_synced(self) -> bool:
        """Block until the informer cache (if any) has its initial snapshot,
        or stop() was called. True = safe to reconcile."""
        if self.cache is None:
            return True
        while not self._stop.is_set():
            if self.cache.wait_for_sync(0.2):
                return True
        return False

    def _prime(self) -> None:
        if not self._wait_cache_synced():
            return
        for job in self.read.list("TPUJob", self.options.namespace):
            self.enqueue(job.metadata.key())

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        if self._watch_q is not None:
            self.store.stop_watch(self._watch_q)
        for t in self._threads:
            t.join(timeout=5)

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def _pump(self) -> None:
        """Direct-watch pump (cache-less wiring only): watch events → job
        keys (≙ the event handlers of :300-339)."""
        while not self._stop.is_set():
            try:
                ev: WatchEvent = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if ev.kind == "Event":
                continue
            # same delivery-context contract the informer path gets from
            # the cache drain: the handler sees the event's origin span
            trace.set_delivery(getattr(ev, "trace", None))
            try:
                self._pump_obj(ev.obj)
            finally:
                trace.clear_delivery()

    def _pump_obj(self, obj) -> None:
        """One object observation → the TPUJob key to reconcile (job events
        enqueue directly; owned-object events enqueue the controller owner
        via the handleObject rule)."""
        ns = obj.metadata.namespace
        if self.options.namespace is not None and ns != self.options.namespace:
            return
        if obj.kind == "TPUJob":
            self._note_trigger(obj.metadata.key())
            self.enqueue(obj.metadata.key())
            return
        owner = self._controller_owner(obj)
        if owner is not None:
            self._note_trigger(f"{ns}/{owner.name}")
            self.enqueue(f"{ns}/{owner.name}")

    def _note_trigger(self, key: str) -> None:
        """Remember the delivering watch event's origin span (if any) as
        the causal parent of the reconcile this enqueue wakes."""
        link = trace.get_delivery()
        if link is not None:
            with self._trace_lock:
                self._trace_links[key] = link

    @staticmethod
    def _controller_owner(obj) -> Optional[OwnerReference]:
        for ref in obj.metadata.owner_references:
            if ref.controller and ref.kind == "TPUJob":
                return ref
        return None

    def _run_worker(self, worker: int = 0) -> None:
        # a worker reconciling against a cold cache would observe an empty
        # world — and e.g. recreate every pod of a live job (AlreadyExists
        # storms) or mark a running job freshly Created
        if not self._wait_cache_synced():
            return
        while True:
            # bounded get (oplint BLK001): the old unbounded get() relied on
            # shut_down()'s notify_all alone to ever unblock this thread —
            # a stop() racing a worker BETWEEN its loop check and the wait
            # was safe, but any future stop path that forgets shut_down()
            # (or a queue bug swallowing the wake) parked the worker forever
            # with no way to observe _stop. The watch pump at _pump already
            # polls at 0.2s for exactly this reason. ``worker`` is the
            # sharded queue's home-shard index (ignored by the single queue).
            key = self.queue.get(timeout=0.2, shard=worker)
            if key is None:
                if self._stop.is_set() or self.queue.shutting_down:
                    return
                continue
            try:
                # sync_handler owns the Conflict/AlreadyExists → requeue
                # mapping (stale cached reads); only unexpected errors
                # reach the backstop below
                ok = self.sync_handler(key)
            except Exception:
                log.exception("sync %s failed", key)
                ok = False
            if ok:
                self.queue.forget(key)
            else:
                self.queue.add_rate_limited(key)
            self.queue.done(key)

    # ------------------------------------------------------------------
    # reconcile (≙ syncHandler :443-608)
    # ------------------------------------------------------------------

    def sync_handler(self, key: str) -> bool:
        """One reconcile. Returns True on success (forget), False to requeue
        (≙ syncHandler returning err → AddRateLimited in processNextWorkItem
        :381-438; Conflicts and ownership errors both requeue).

        The reconcile runs under a ``controller.reconcile`` span parented
        on the watch write that enqueued this key (the causal "why"), and
        its wall time lands in the reconcile-latency histogram where the
        span closes."""
        with self._trace_lock:
            link = self._trace_links.pop(key, None)
        t0 = time.perf_counter()
        try:
            with trace.start_span(
                "controller.reconcile", parent=link, attrs={"job": key}
            ):
                return self._sync(key)
        except (Conflict, AlreadyExists):
            # Conflict: stale read lost an update race. AlreadyExists: the
            # cache had not yet observed a dependent this controller created
            # moments ago (the informer lag client-go controllers absorb the
            # same way) — requeue; the rate limiter spaces the retry past
            # the watch latency.
            return False
        except RuntimeError as e:
            log.warning("sync %s: %s", key, e)
            return False
        finally:
            dt = time.perf_counter() - t0
            metrics.reconcile_latency.observe(dt)
            log.debug("sync %s took %.1fms", key, dt * 1e3)

    def _sync(self, key: str) -> bool:
        namespace, name = key.split("/", 1)
        job = self.read.try_get("TPUJob", namespace, name)
        if job is None:
            with self._port_lock:  # release the port reservation
                self._ports_inflight.pop(key, None)
            # ≙ the kube garbage collector's cascade delete: the job is
            # gone, so every dependent it owned must go too. Before this,
            # deleting a RUNNING job stranded its pods (and their worker
            # processes) forever — the orphan the chaos invariant checker
            # flags (tests/invariants.py no_orphaned_dependents).
            self._reap_orphans(namespace, name)
            return True  # deleted; nothing left to do (≙ :460-467)
        set_defaults(job)  # store returned a deep copy (≙ DeepCopy + Default :470-475)

        errs = validate_tpujob(job)
        if errs:
            # invalid specs are dropped, not requeued (≙ :482-487)
            self.recorder.event(job, WARNING, EVENT_VALIDATION_ERROR, "; ".join(errs))
            return True

        if not cond.is_finished(job.status):
            self._ensure_trace_id(job)

        workers = self._list_workers(job)

        if cond.is_finished(job.status):
            self._cleanup_finished(job, workers)
            return True

        # --- suspend (RunPolicy.Suspend; implemented, unlike the reference) ---
        if job.spec.run_policy.suspend:
            return self._sync_suspended(job, workers)
        if cond.is_suspended(job.status):
            cond.update_job_conditions(
                job.status, ConditionType.SUSPENDED, cond.REASON_RESUMED, "resumed", False
            )
            self.recorder.event(job, NORMAL, cond.REASON_RESUMED, "job resumed")

        # --- Created condition + start time (≙ :532-543) ---
        if cond.update_job_conditions(
            job.status,
            ConditionType.CREATED,
            cond.REASON_CREATED,
            f"TPUJob {key} is created",
        ):
            metrics.jobs_created.inc()
            self.recorder.event(job, NORMAL, cond.REASON_CREATED, "job created")
        cond.ensure_timestamps(job.status)

        # --- activeDeadlineSeconds (RunPolicy; SURVEY.md §5.3 gap, closed) ---
        deadline = job.spec.run_policy.active_deadline_seconds
        if (
            deadline is not None
            and job.status.start_time is not None
            and time.time() - job.status.start_time > deadline
        ):
            self._fail_job(
                job,
                workers,
                cond.REASON_DEADLINE,
                f"job exceeded activeDeadlineSeconds={deadline}",
            )
            return self._write_status(job)

        # --- gang placement (≙ getOrCreatePodGroups :572-576 + ICI layout) ---
        try:
            placement = place_workers(job.spec.slice, job.spec.worker.replicas)
        except PlacementError as e:
            self.recorder.event(job, WARNING, EVENT_PLACEMENT_ERROR, str(e))
            return True  # spec problem: drop like a validation error

        # --- dependents, all idempotent getOrCreate ---
        self._get_or_create_service(job)
        self._get_or_create_configmap(job, workers)
        if self.options.gang_scheduling:
            self._get_or_create_podgroup(job)
        workers = self._reconcile_workers(job, placement)

        # --- status mirror (≙ updateMPIJobStatus call :602) ---
        self._update_status(job, workers)
        return self._write_status(job)

    def _ensure_trace_id(self, job: TPUJob) -> None:
        """The job's trace anchor: admission (api/client.py) stamps the
        ``tpujob.dev/trace-id`` annotation; this backstop covers jobs
        created straight through the store (tests, benches, old clients).
        Either way, the current reconcile span re-homes into the job's
        trace so everything this pass causes groups under it."""
        tid = job.metadata.annotations.get(trace.ANNOTATION_TRACE_ID)
        if not tid:
            # memo by uid: a cached read lagging our own stamp must reuse
            # the minted id, not write a fresh one per reconcile until the
            # informer echo lands (under _trace_lock — worker threads
            # trimming the bounded memo concurrently must not double-pop)
            with self._trace_lock:
                tid = self._stamped_traces.get(job.metadata.uid)
        if not tid:
            tid = trace.new_trace_id()
            try:
                self.store.patch(
                    "TPUJob", job.namespace, job.name,
                    # uid-pinned like every identity-sensitive write: a
                    # recreated same-name job must mint its own trace
                    {"metadata": {
                        "uid": job.metadata.uid,
                        "annotations": {trace.ANNOTATION_TRACE_ID: tid},
                    }},
                )
            except (NotFound, Conflict):
                return  # deleted/recreated under us; next reconcile retries
            with self._trace_lock:
                self._stamped_traces[job.metadata.uid] = tid
                while len(self._stamped_traces) > 4096:
                    self._stamped_traces.pop(
                        next(iter(self._stamped_traces))
                    )
        job.metadata.annotations[trace.ANNOTATION_TRACE_ID] = tid
        sp = trace.TRACER.current_span()
        if sp is not None:
            sp.adopt_trace(tid)

    # ------------------------------------------------------------------
    # dependents
    # ------------------------------------------------------------------

    def _reap_orphans(self, namespace: str, name: str) -> None:
        """Delete every dependent of a deleted job. Selection is by the
        job-name label every dependent carries, guarded by the controller
        owner ref (never GC an object some other owner claims); reads ride
        the lister, so a job with no leftovers costs zero store traffic.
        Idempotent and level-triggered: each dependent's own DELETED event
        re-enqueues this job key until nothing is left."""
        for kind in ("Pod", "ConfigMap", "Service", "PodGroup"):
            for obj in self.read.list(
                kind, namespace, selector={LABEL_JOB_NAME: name}
            ):
                owner = self._controller_owner(obj)
                if owner is None or owner.name != name:
                    continue
                self.store.try_delete(kind, namespace, obj.metadata.name)

    def _owner_ref(self, job: TPUJob) -> OwnerReference:
        return OwnerReference(name=job.name, uid=job.metadata.uid, controller=True)

    def _check_owned(self, job: TPUJob, obj) -> bool:
        """Adoption check (≙ :625-631): an existing dependent not controlled
        by this job is a fatal ownership conflict → warning event + requeue."""
        owner = self._controller_owner(obj)
        if owner is None or owner.uid != job.metadata.uid:
            msg = (
                f"{obj.kind} {obj.metadata.key()} already exists and is not "
                f"controlled by TPUJob {job.name}"
            )
            self.recorder.event(job, WARNING, "IneligibleOwnership", msg)
            raise RuntimeError(msg)
        return True

    def _selector(self, job: TPUJob) -> Dict[str, str]:
        return {LABEL_JOB_NAME: job.name}

    def _list_workers(self, job: TPUJob) -> List[Pod]:
        pods = self.read.list("Pod", job.namespace, selector=self._selector(job))
        pods.sort(key=lambda p: int(p.metadata.labels.get(LABEL_REPLICA_INDEX, "0")))
        return pods

    def _get_or_create_service(self, job: TPUJob) -> Service:
        """Headless service giving workers stable DNS (≙ newWorkersService
        :1141-1171)."""
        existing = self.read.try_get("Service", job.namespace, job.service_name())
        if existing is not None:
            self._check_owned(job, existing)
            return existing
        svc = Service(
            metadata=ObjectMeta(
                name=job.service_name(),
                namespace=job.namespace,
                labels=self._selector(job),
                owner_references=[self._owner_ref(job)],
            ),
            spec=ServiceSpec(cluster_ip="None", selector=self._selector(job)),
        )
        return self.store.create(svc)

    # ports probed above options.coordinator_port before wrapping
    PORT_RANGE = 1024
    # max age of the used-port snapshot (seconds): _ports_inflight covers
    # everything this leader assigned, so the snapshot only needs to age
    # fast enough to learn a PREVIOUS leader's assignments after failover
    _PORTS_SNAPSHOT_TTL = 30.0

    def _assign_coordinator_port(self, job: TPUJob) -> int:
        """Per-job rendezvous port, recorded in status (once assigned it is
        stable for the job's lifetime — workers compiled against it must
        find the same coordinator after every gang restart). Hash-placed in
        [base, base+PORT_RANGE) with linear probing against the ports of
        other live jobs; the reference needs no analogue because every pod
        has its own DNS name, whereas one LocalExecutor host shares one
        loopback interface."""
        key = job.metadata.key()
        with self._port_lock:
            if job.status.coordinator_port:
                self._ports_inflight[key] = job.status.coordinator_port
                return job.status.coordinator_port
            reserved = self._ports_inflight.get(key)
            if reserved is not None:
                # a prior attempt whose status write lost a Conflict: the
                # pods already carry this port, so it must stick
                job.status.coordinator_port = reserved
                return reserved
        # list OUTSIDE the lock (LCK001): self.read is a raw store when no
        # cache is wired, and a network round-trip under _port_lock would
        # serialize every concurrent reconcile behind it. Sound because a
        # concurrent assignment ALWAYS lands in _ports_inflight under the
        # lock before its status write — re-checked below — so a port
        # missing from this (possibly stale) snapshot cannot be lost.
        # The snapshot is a TTL-cached PORT SET (10k-job round): one full
        # list per NEW job made first-assignment cost O(jobs²) across a
        # submission storm, and caching the deepcopied job objects still
        # cost O(jobs) per refresh — the set of busy ports is all this
        # probe needs. A port freshly assigned by THIS controller is
        # always visible through _ports_inflight regardless of snapshot
        # age (the leader is the only assigner), so staleness only risks
        # probing onto a port a *finished* job recently freed — harmless:
        # assignment is best-effort hash probing by design.
        now = time.monotonic()
        with self._port_lock:
            snap = self._ports_snapshot
        if snap is None or now - snap[1] > self._PORTS_SNAPSHOT_TTL:
            listed = {
                (j.metadata.uid, j.status.coordinator_port)
                for j in self.read.list("TPUJob")
                if j.status.coordinator_port
                and not cond.is_finished(j.status)
            }
            with self._port_lock:
                self._ports_snapshot = (listed, now)
                snap = self._ports_snapshot
        with self._port_lock:
            reserved = self._ports_inflight.get(key)
            if reserved is not None:
                job.status.coordinator_port = reserved
                return reserved
            used = {
                p for uid, p in snap[0] if uid != job.metadata.uid
            }
            used |= {
                p for k, p in self._ports_inflight.items() if k != key
            }
            base = self.options.coordinator_port
            start = zlib.crc32(key.encode()) % self.PORT_RANGE
            port = base + start  # all taken: best effort
            for probe in range(self.PORT_RANGE):
                cand = base + (start + probe) % self.PORT_RANGE
                if cand not in used:
                    port = cand
                    break
            self._ports_inflight[key] = port
            job.status.coordinator_port = port
            return port

    def coordinator_address(self, job: TPUJob) -> str:
        return f"{job.worker_hostname(0)}:{self._assign_coordinator_port(job)}"

    def _config_data(self, job: TPUJob, workers: List[Pod]) -> Dict[str, str]:
        """hostfile + discover_hosts.sh parity (≙ newConfigMap :1088-1113 and
        updateDiscoverHostsInConfigMap :1116-1138: static hostfile of stable
        DNS names; dynamic script listing only *Running* pods, sorted)."""
        slots = job.spec.slots_per_worker
        hostfile = "".join(
            f"{job.worker_hostname(i)} slots={slots}\n"
            for i in range(job.spec.worker.replicas)
        )
        running = sorted(
            int(p.metadata.labels[LABEL_REPLICA_INDEX])
            for p in workers
            if p.status.phase == PodPhase.RUNNING
        )
        discover = "#!/bin/sh\n" + "".join(
            f"echo {job.worker_hostname(i)}:{slots}\n" for i in running
        )
        data = {
            CONFIG_HOSTFILE: hostfile,
            CONFIG_DISCOVER_HOSTS: discover,
            CONFIG_COORDINATOR: self.coordinator_address(job),
        }
        req = job.metadata.annotations.get(ANNOTATION_PROFILE_REQUEST, "")
        if req:
            data[CONFIG_PROFILE] = req
        return data

    def _get_or_create_configmap(self, job: TPUJob, workers: List[Pod]) -> ConfigMap:
        data = self._config_data(job, workers)
        existing = self.read.try_get("ConfigMap", job.namespace, job.config_name())
        if existing is not None:
            self._check_owned(job, existing)
            if existing.data != data:
                # merge-patch of just the changed keys (nulls delete):
                # one request, and a cached copy lagging our own last
                # write can never 409 the reconcile
                return self.store.patch(
                    "ConfigMap", job.namespace, job.config_name(),
                    {"data": diff_merge_patch(existing.data, data)},
                )
            metrics.store_writes_elided.inc(component="controller")
            return existing
        cm = ConfigMap(
            metadata=ObjectMeta(
                name=job.config_name(),
                namespace=job.namespace,
                labels=self._selector(job),
                owner_references=[self._owner_ref(job)],
            ),
            data=data,
        )
        return self.store.create(cm)

    @staticmethod
    def _desired_min_member(job: TPUJob) -> int:
        sp = job.spec.run_policy.scheduling_policy
        if sp and sp.min_available is not None:
            return sp.min_available
        return job.spec.worker.replicas

    def _get_or_create_podgroup(self, job: TPUJob) -> PodGroup:
        """Gang unit: min_member == workers — all-or-nothing slice allocation
        (≙ newPodGroup :1215-1237 with minMember = workers+1 :573; no +1 here
        because there is no launcher pod). A schedulingPolicy.minAvailable
        overrides, on both the create and the reconcile-update path."""
        desired = self._desired_min_member(job)
        existing = self.read.try_get("PodGroup", job.namespace, job.podgroup_name())
        if existing is not None:
            self._check_owned(job, existing)
            if existing.spec.min_member != desired:
                return self.store.patch(
                    "PodGroup", job.namespace, job.podgroup_name(),
                    {"spec": {"min_member": desired}},
                )
            return existing
        sp = job.spec.run_policy.scheduling_policy
        pg = PodGroup(
            metadata=ObjectMeta(
                name=job.podgroup_name(),
                namespace=job.namespace,
                labels=self._selector(job),
                owner_references=[self._owner_ref(job)],
            ),
            spec=PodGroupSpec(
                min_member=desired,
                priority_class=sp.priority_class if sp else "",
            ),
        )
        return self.store.create(pg)

    def _new_worker(self, job: TPUJob, index: int, placement: SlicePlacement) -> Pod:
        """≙ newWorker (:1246-1296): stable hostname/subdomain behind the
        headless service, labels for selection, controller env injected after
        user env (controller values win for the rendezvous contract)."""
        tmpl = job.spec.worker.template
        container = Container.from_dict(tmpl.container.to_dict())
        env = dict(container.env)
        env.update(
            {
                ENV_JOB_NAME: job.name,
                ENV_NAMESPACE: job.namespace,
                ENV_COORDINATOR: self.coordinator_address(job),
                ENV_NUM_HOSTS: str(job.spec.worker.replicas),
                ENV_HOST_ID: str(index),
                ENV_CHIPS_PER_HOST: str(job.spec.slice.chips_per_host),
                ENV_ACCELERATOR: job.spec.slice.accelerator,
                ENV_TOPOLOGY: "x".join(map(str, placement.topology)),
                ENV_HOST_MESH: "x".join(map(str, placement.host_mesh)),
                ENV_HOST_COORD: "x".join(map(str, placement.host_coords[index])),
                ENV_SLICE_ID: str(placement.slice_ids[index]),
                ENV_NUM_SLICES: str(placement.num_slices),
                ENV_COMPILE_CACHE: (
                    "0" if job.spec.compile_cache is False else "1"
                ),
            }
        )
        container.env = env
        labels = dict(tmpl.labels)
        labels.update(self._selector(job))
        labels[LABEL_ROLE] = ROLE_WORKER
        labels[LABEL_REPLICA_INDEX] = str(index)
        # restart_generation, NOT restart_count: free preemption restarts
        # don't burn the backoff budget but ARE new launch generations —
        # labeling them with the unchanged count would blind the
        # single-generation invariant in exactly the preemption scenarios
        # the chaos suite injects
        labels[LABEL_GENERATION] = str(job.status.restart_generation)
        annotations = dict(tmpl.annotations)
        annotations.update(placement.annotations_for(index))
        # trace propagation: the pod carries its job's trace id, so every
        # component holding the pod (scheduler bind, agent launch, monitor
        # eviction) can open spans in the job's trace with no live header
        # chain — robust across the process crashes chaos injects
        tid = job.metadata.annotations.get(trace.ANNOTATION_TRACE_ID)
        if tid:
            annotations[trace.ANNOTATION_TRACE_ID] = tid
        # ExitCode policy is controller-owned: the pod itself never restarts
        # (≙ setRestartPolicy :1394-1400)
        pod_restart = (
            RestartPolicy.NEVER
            if job.spec.worker.restart_policy == RestartPolicy.EXIT_CODE
            else job.spec.worker.restart_policy
        )
        return Pod(
            metadata=ObjectMeta(
                name=job.worker_name(index),
                namespace=job.namespace,
                labels=labels,
                annotations=annotations,
                owner_references=[self._owner_ref(job)],
            ),
            spec=PodSpec(
                container=container,
                hostname=job.worker_name(index),
                subdomain=job.service_name(),
                restart_policy=pod_restart,
                node_selector=dict(tmpl.node_selector),
                scheduler_name=tmpl.scheduler_name,
                priority_class=tmpl.priority_class
                or (
                    job.spec.run_policy.scheduling_policy.priority_class
                    if job.spec.run_policy.scheduling_policy
                    else ""
                ),
            ),
        )

    def _reconcile_workers(self, job: TPUJob, placement: SlicePlacement) -> List[Pod]:
        """Per-index get-or-create + elastic scale-down of indices >= replicas
        (≙ getOrCreateWorker :817-877, scale-down :833-849).

        Under ExitCode policy, a RUNNING over-index pod is left to exit on
        its own: the elastic protocol has every worker observe the shrunken
        hostfile and exit EXIT_RESTART at the *same gang-synchronized step*
        (ops/elastic.py). Killing it here would sever a live collective and
        crash the survivors with a permanent (non-75) exit code. The
        reference can kill immediately because Horovod re-forms rings around
        lost peers; an XLA gang cannot."""
        replicas = job.spec.worker.replicas
        graceful = job.spec.worker.restart_policy == RestartPolicy.EXIT_CODE
        existing = {p.metadata.name: p for p in self._list_workers(job)}
        # scale-UP grace, symmetric to the scale-down grace below: a worker
        # created into a still-running old gang cannot join its rendezvous
        # (the live coordinator was started with the old process count) and
        # would crash non-retryably. While any old-size pod is RUNNING,
        # defer new creations; the drain restart relaunches the full gang.
        old_gang_live = graceful and any(
            p.status.phase == PodPhase.RUNNING
            and p.spec.container.env.get(ENV_NUM_HOSTS) != str(replicas)
            for p in existing.values()
        )
        out: List[Pod] = []
        for i in range(replicas):
            name = job.worker_name(i)
            pod = existing.pop(name, None)
            if pod is None:
                if old_gang_live:
                    continue
                pod = self.store.create(self._new_worker(job, i, placement))
            else:
                self._check_owned(job, pod)
            out.append(pod)
        # anything left in `existing` has index >= replicas → scale down
        for name, pod in existing.items():
            self._check_owned(job, pod)
            if graceful and pod.status.phase == PodPhase.RUNNING:
                continue  # it will exit EXIT_RESTART itself; reap next sync
            self.store.try_delete("Pod", job.namespace, name)
        return out

    # ------------------------------------------------------------------
    # status (≙ updateMPIJobStatus :921-996, launcher→worker-0)
    # ------------------------------------------------------------------

    def _update_status(self, job: TPUJob, workers: List[Pod]) -> None:
        rs = ReplicaStatus()
        for p in workers:
            if p.status.phase == PodPhase.RUNNING:
                rs.active += 1
            elif p.status.phase == PodPhase.SUCCEEDED:
                rs.succeeded += 1
            elif p.status.phase == PodPhase.FAILED:
                rs.failed += 1
                if p.is_evicted():
                    rs.evicted += 1
        job.status.replica_statuses = {ReplicaType.WORKER: rs}

        replicas = job.spec.worker.replicas
        coordinator = next(
            (p for p in workers if p.metadata.labels.get(LABEL_REPLICA_INDEX) == "0"),
            None,
        )
        if coordinator is not None:
            metrics.job_info.set(
                1, coordinator=coordinator.metadata.name, namespace=job.namespace
            )

        # --- success: coordinator (worker 0) exited 0 (≙ launcher Succeeded) ---
        if coordinator is not None and coordinator.status.phase == PodPhase.SUCCEEDED:
            if cond.update_job_conditions(
                job.status,
                ConditionType.SUCCEEDED,
                cond.REASON_SUCCEEDED,
                f"TPUJob {job.metadata.key()} successfully completed",
            ):
                metrics.jobs_successful.inc()
                self.recorder.event(job, NORMAL, cond.REASON_SUCCEEDED, "job succeeded")
            cond.ensure_timestamps(job.status)
            return

        # --- failures: gang-coherent restart (≙ :935-983, redesigned) ---
        # The reference restarts per-pod because Horovod re-forms rings
        # around lost peers. An XLA gang cannot: losing one member makes the
        # survivors' collectives fail with ordinary (non-retryable) exit
        # codes. So failure handling is gang-scoped: if ANY pod failed
        # retryably (evicted, exit>=128, EXIT_RESTART), companion failures
        # are collateral and the WHOLE gang restarts — but the fail-vs-
        # restart VERDICT waits until no pod is still running (drain: peers
        # exit via the elastic protocol or their own collective error;
        # activeDeadlineSeconds backstops a straggler that never exits).
        # The drain sync executes the restart exactly once per generation,
        # so backoffLimit counts restart generations, not per-pod failure
        # observations.
        failed = [p for p in workers if p.status.phase == PodPhase.FAILED]
        if failed:
            retryable = any(self._pod_retryable(job, p) for p in failed)
            all_pods = self._list_workers(job)  # incl. over-index stragglers
            # a maintenance-evicted member marks the whole generation as a
            # MIGRATION (the planned-disruption flavor of Restarting): the
            # condition machine treats the two restart-ish states as one
            # slot, so `ctl describe` shows Migrating while the
            # checkpoint-then-migrate drains and relaunches
            migrating = retryable and any(
                p.status.reason == REASON_MAINTENANCE for p in failed
            )
            if migrating and cond.update_job_conditions(
                job.status,
                ConditionType.MIGRATING,
                cond.REASON_MIGRATING,
                f"gang is migrating off a draining node "
                f"({failed[0].status.message or 'maintenance'})",
            ):
                self.recorder.event(
                    job, NORMAL, cond.REASON_MIGRATING,
                    "gang migrating off a draining node",
                )
            elif not migrating and retryable and cond.update_job_conditions(
                job.status,
                ConditionType.RESTARTING,
                cond.REASON_RESTARTING,
                "worker pod(s) failed retryably; gang will restart",
            ):
                self.recorder.event(
                    job, WARNING, cond.REASON_RESTARTING, "job restarting"
                )
            cond.ensure_timestamps(job.status)
            if any(p.status.phase == PodPhase.RUNNING for p in all_pods):
                # drain before the VERDICT, not just before the restart: a
                # companion's ordinary crash often lands before the root
                # cause is recorded (a lost node's pods are only marked
                # Evicted after the heartbeat grace window — NodeMonitor),
                # so deciding fail-vs-restart now would misread collateral
                # rc=1 exits as a permanent app failure. Survivors exit on
                # their own (collective error / elastic protocol);
                # activeDeadlineSeconds backstops a straggler.
                if not retryable:
                    self._note_drain_wait(job, failed)
                return
            if retryable:
                # Preemption is the scheduler's doing, not the workload
                # failing: a preempted generation restarts for free — it
                # neither burns backoffLimit nor counts as a restart (kube
                # preemption never charges a Job's restart policy either).
                # A busy cluster preempting a low-priority job 3 times must
                # not permanently FAIL it with backoffLimit=2. The free pass
                # requires every RETRYABLE failure in the generation to be a
                # PLANNED disruption (preemption or a drain's maintenance
                # migration) — non-retryable companions (rc=1 collective
                # errors) are collateral of the eviction, but a pod that
                # failed retryably on its own (exit 137, EXIT_RESTART)
                # means the workload was crashing anyway and the generation
                # must still count toward backoffLimit.
                preempted = any(
                    p.is_planned_disruption() for p in failed
                ) and all(
                    p.is_planned_disruption()
                    or not self._pod_retryable(job, p)
                    for p in failed
                )
                backoff = job.spec.run_policy.backoff_limit
                if (
                    not preempted
                    and backoff is not None
                    and job.status.restart_count >= backoff
                ):
                    self._fail_job(
                        job,
                        workers,
                        cond.REASON_BACKOFF,
                        f"restart count {job.status.restart_count} reached "
                        f"backoffLimit={backoff}",
                    )
                    return
                if not preempted:
                    job.status.restart_count += 1
                    metrics.jobs_restarted.inc()
                # every EXECUTED generation restart counts here, free
                # preemption restarts included: the restart-storm tripwire
                # (tests/test_stress.py) and the `ctl`-visible rate ride
                # this, and a storm of "free" restarts is still a storm
                job.status.restart_generation += 1
                metrics.gang_restarts.inc()
                # a restart executed: the next generation gets its own
                # drain-wait note even when the restart was free (the
                # (uid, restart_count) key would otherwise collide across
                # preempted generations and suppress the once-per-generation
                # hang explanation)
                self._drain_noted.discard(
                    (job.metadata.uid, job.status.restart_count)
                )
                # the gang-restart span (an `ctl trace --last-incident`
                # anchor): child of this reconcile — whose parent is the
                # eviction/failure write that triggered it — and parent of
                # the teardown deletes below, so the relaunch chain the
                # deletes cause links back to the restart that caused THEM
                first_fail = failed[0]
                with trace.start_span(
                    "controller.gang_restart",
                    attrs={
                        "job": job.metadata.key(),
                        "generation": job.status.restart_generation,
                        "free": preempted,
                        "first_failed": first_fail.metadata.name,
                        "reason": first_fail.status.reason or "Error",
                    },
                ):
                    # delete every terminal pod — a succeeded
                    # non-coordinator must re-run too, or the relaunched
                    # gang waits on a member that never comes back; next
                    # reconcile recreates the gang at the (possibly
                    # rescaled) size
                    for p in all_pods:
                        self.store.try_delete(
                            "Pod", p.metadata.namespace, p.metadata.name
                        )
                return
            first = failed[0]
            reason = cond.REASON_EVICTED if first.is_evicted() else cond.REASON_FAILED
            msg = (
                f"worker pod {first.metadata.name} failed with reason "
                f"{first.status.reason or 'Error'}: {first.status.message or ''}"
            )
            self._fail_job(job, workers, reason, msg)
            return

        # --- running: every worker Running (≙ worker-readiness→Running,
        # mpi_job_controller_test.go:771-935) ---
        if replicas and rs.active == replicas:
            if cond.update_job_conditions(
                job.status,
                ConditionType.RUNNING,
                cond.REASON_RUNNING,
                f"all {replicas} workers are running",
            ):
                self.recorder.event(job, NORMAL, cond.REASON_RUNNING, "job running")

    def _pod_retryable(self, job: TPUJob, pod: Pod) -> bool:
        """Eviction/preemption is always retryable (TPU preemption is routine;
        ≙ the evicted-requeue of syncHandler :506-529). Otherwise the replica
        restart policy decides; ExitCode retries system exit codes >= 128
        (SIGKILL'd / infrastructure, matching kubeflow-common convention) and
        EXIT_RESTART (75, EX_TEMPFAIL) — the elastic protocol's own
        "re-run me at the new gang size" code (ops/elastic.py; ≙ the
        discover_hosts.sh re-form loop, SURVEY.md §3.5)."""
        if pod.is_evicted():
            return True
        rp = job.spec.worker.restart_policy
        if rp in (RestartPolicy.ALWAYS, RestartPolicy.ON_FAILURE):
            return True
        if rp == RestartPolicy.EXIT_CODE:
            ec = pod.status.exit_code
            return ec is not None and (ec >= 128 or ec == EXIT_RESTART)
        return False

    def _note_drain_wait(self, job: TPUJob, failed: List[Pod]) -> None:
        """Non-retryable failure observed while peers still run: the verdict
        waits for drain (a late node-loss eviction can still flip it to a
        restart). Say so ONCE per generation in the event trail — without
        activeDeadlineSeconds, a survivor that never exits would otherwise
        leave the job hanging with no visible explanation."""
        key = (job.metadata.uid, job.status.restart_count)
        if key in self._drain_noted:
            return
        if len(self._drain_noted) > 1024:
            self._drain_noted.clear()  # bounded; a re-note is benign
        self._drain_noted.add(key)
        first = failed[0]
        self.recorder.event(
            job, WARNING, "TPUJobDraining",
            f"worker pod {first.metadata.name} failed "
            f"({first.status.reason or 'Error'}); waiting for the remaining "
            f"workers to drain before the fail-vs-restart verdict — set "
            f"runPolicy.activeDeadlineSeconds to bound this wait",
        )

    def _fail_job(
        self, job: TPUJob, workers: List[Pod], reason: str, message: str
    ) -> None:
        if cond.update_job_conditions(
            job.status, ConditionType.FAILED, reason, message
        ):
            metrics.jobs_failed.inc()
            self.recorder.event(job, WARNING, reason, message)
        cond.ensure_timestamps(job.status)

    # ------------------------------------------------------------------
    # finished / suspend handling
    # ------------------------------------------------------------------

    def _sync_suspended(self, job: TPUJob, workers: List[Pod]) -> bool:
        for p in workers:
            self.store.try_delete("Pod", p.metadata.namespace, p.metadata.name)
        self.store.try_delete("PodGroup", job.namespace, job.podgroup_name())
        if cond.update_job_conditions(
            job.status,
            ConditionType.SUSPENDED,
            cond.REASON_SUSPENDED,
            "job is suspended",
        ):
            self.recorder.event(job, NORMAL, cond.REASON_SUSPENDED, "job suspended")
        rs = job.status.replica_statuses.setdefault(ReplicaType.WORKER, ReplicaStatus())
        rs.active = 0
        return self._write_status(job)

    def _cleanup_finished(self, job: TPUJob, workers: List[Pod]) -> None:
        """≙ the finished branch of syncHandler (:492-530): apply
        cleanPodPolicy, drop the gang, honor ttlSecondsAfterFinished."""
        policy = job.spec.run_policy.clean_pod_policy
        for p in workers:
            delete = policy == CleanPodPolicy.ALL or (
                policy == CleanPodPolicy.RUNNING and p.status.phase == PodPhase.RUNNING
            )
            if delete:
                self.store.try_delete("Pod", p.metadata.namespace, p.metadata.name)
        self.store.try_delete("PodGroup", job.namespace, job.podgroup_name())

        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None and job.status.completion_time is not None:
            age = time.time() - job.status.completion_time
            if age >= ttl:
                self.store.try_delete("TPUJob", job.namespace, job.name)
            else:
                self.queue.add_after(job.metadata.key(), ttl - age + 0.01)

    # ------------------------------------------------------------------
    # housekeeping: Event TTL sweep (≙ the apiserver's event TTL — kube
    # prunes its events after 1h; without this the append-only audit
    # stream grows the store without bound)
    # ------------------------------------------------------------------

    def _housekeeping_loop(self) -> None:
        while not self._stop.wait(self.options.event_gc_interval):
            try:
                self.prune_events()
            except Exception:
                log.exception("event TTL sweep failed")  # next pass retries

    def prune_events(self, now: Optional[float] = None) -> int:
        """Delete Events older than ``options.event_ttl``; returns the
        pruned count (also exported as tpu_operator_events_pruned_total).
        Recent events — the trail `ctl describe`/`ctl events` renders —
        survive untouched; reads go straight to the store because Events
        are deliberately not informer-cached (cache.DEFAULT_KINDS)."""
        ttl = self.options.event_ttl
        if ttl is None:
            return 0
        cutoff = (time.time() if now is None else now) - ttl
        pruned = 0
        for ev in self.store.list("Event", self.options.namespace):
            if ev.timestamp and ev.timestamp < cutoff:
                if self.store.try_delete(
                    "Event", ev.metadata.namespace, ev.metadata.name
                ) is not None:
                    pruned += 1
        if pruned:
            metrics.events_pruned.inc(pruned)
            log.info("event TTL sweep pruned %d events (ttl %.0fs)",
                     pruned, ttl)
        return pruned

    # ------------------------------------------------------------------
    # status write (injectable; ≙ updateStatusHandler :243-244)
    # ------------------------------------------------------------------

    def _default_write_status(self, job: TPUJob) -> bool:
        """Persist status only when it changed (≙ UpdateStatus-on-change,
        :602 + :921-996 tail — the no-op elision that keeps an idle
        cluster at ZERO store writes, the write-side twin of the lister's
        zero-read guarantee), via ONE status-subresource merge-patch
        carrying just the changed keys (nulls for removed ones). No rv
        precondition: this controller is the only TPUJob-status writer
        (leader-elected), so patching latest is exactly right and the old
        GET+PUT Conflict/requeue cycle disappears."""
        stored = self.read.try_get("TPUJob", job.namespace, job.name)
        if stored is None:
            return True
        if stored.metadata.uid != job.metadata.uid:
            # the job this reconcile computed for was deleted and a new
            # same-name incarnation exists: stamping the OLD incarnation's
            # status (restart_count, Failed/Restarting conditions) onto the
            # fresh job would e.g. pre-burn its backoffLimit — and the
            # absorbed restart_count would never self-heal
            return True
        old, new = stored.status.to_dict(), job.status.to_dict()
        # train_telemetry is the goodput aggregator's field — this
        # controller NEVER writes it, so it must never appear in the
        # diff: a reconcile snapshot that predates the aggregator's
        # rollup patch would otherwise emit train_telemetry: null (or a
        # stale blob) and erase the other writer's work
        old.pop("train_telemetry", None)
        new.pop("train_telemetry", None)
        if old == new:
            metrics.store_writes_elided.inc(component="controller")
            return True
        try:
            self.store.patch(
                "TPUJob", job.namespace, job.name,
                # uid-pinned (checked atomically with the merge): the
                # recreation race between the read above and this write —
                # or a deposed leader's in-flight write landing over the
                # new leader's — bounces as Conflict instead of silently
                # cross-stamping incarnations
                {"status": diff_merge_patch(old, new),
                 "metadata": {"uid": job.metadata.uid}},
                subresource="status",
            )
        except NotFound:
            return True  # deleted under us; nothing to mirror
        except Conflict:
            return False  # recreated under us: requeue reads the new world
        return True
