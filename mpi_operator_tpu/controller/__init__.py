"""The TPUJob controller/reconciler.

≙ /root/reference/v2/pkg/controller/ — the core of the reference operator.
"""

from mpi_operator_tpu.controller.controller import (  # noqa: F401
    ControllerOptions,
    TPUJobController,
)
from mpi_operator_tpu.controller.placement import SlicePlacement, place_workers  # noqa: F401
