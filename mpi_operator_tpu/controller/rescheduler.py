"""The goodput-aware defragmenting rescheduler (ISSUE 18).

The scheduler's least-loaded spread is the right day-one policy — it
minimizes blast radius — but a day of diurnal serve scaling, batch
arrivals and maintenance churn leaves chips scattered: total-free stays
ample while no single node can host the next gang member
(``tpu_operator_schedulable_contiguous_chips`` collapses toward 1).
Nothing in the operator moved work *proactively*: migration existed only
as a reaction to a maintenance notice (the disruption plane, ISSUE 14).

This controller closes that gap, leader-only and level-triggered like
every reconciler. Each pass it:

- exports the fragmentation gauges (largest contiguous free block +
  total free chips) so the soak bench and `ctl top --fragmentation`
  judge the same numbers it acts on;
- moves gangs with a goodput-plane-named straggler (ISSUE 15) off the
  suspected-sick host: the node is stamped with
  ``tpujob.dev/straggler-node`` (the scheduler deprioritizes flagged
  nodes — middle tier between clean and maintenance-doomed) and the
  whole gang is evicted through the free checkpoint-then-migrate seam;
- defragments: when a queued gang fits total-free but not
  contiguous-free (or an idle consolidation would raise the contiguous
  block by ``min_gain_chips``), the cheapest all-batch victim node gets
  a short maintenance window stamped on it — the DrainController then
  owns the evacuation end to end (cordon, budgeted free migration,
  deadline escalation) — and once the victim is empty the rescheduler
  uncordons it, returning one whole-node block to the pool.

Every action is governed: a per-window migration cap, per-gang and
per-node hysteresis (no ping-pong on an oscillating fleet), a minimum
contiguous-chips gain for idle consolidation, never a gang that is
already Migrating/Restarting (no second teardown mid-checkpoint), and
never a node hosting serve replicas (disruption budgets stay untouched
by construction — serve migration belongs to the drain/serve planes).
Every eviction rides ``reason=Maintenance``, so restart_generation
advances and restart_count does NOT: a rescheduler that burned retry
budgets would be a reliability hazard, not an optimizer. When a move is
wanted but governance parks it, an explaining Event lands on the
involved object and ``tpu_operator_rescheduler_parked`` counts it.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from mpi_operator_tpu.api.conditions import has_condition
from mpi_operator_tpu.api.types import ConditionType
from mpi_operator_tpu.machinery.events import NORMAL, WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    ANNOTATION_STRAGGLER_NODE,
    NODE_NAMESPACE,
    REASON_MAINTENANCE,
    evict_pod,
)
from mpi_operator_tpu.machinery.store import NotFound
from mpi_operator_tpu.opshell import metrics
from mpi_operator_tpu.scheduler.gang import (
    LABEL_JOB_NAME,
    GangScheduler,
    pod_cost,
)

log = logging.getLogger("tpujob.rescheduler")

LABEL_SERVE_NAME = "tpujob.dev/serve-name"

EVENT_RESCHEDULED = "GangRescheduled"
EVENT_DEFRAG_DRAINING = "DefragDraining"
EVENT_DEFRAG_COMPLETE = "DefragComplete"
EVENT_PARKED = "ReschedulingParked"


class Rescheduler:
    """Leader-only fragmentation/straggler reconciler; see module doc.

    Knobs (the governance surface the README documents):

    - ``min_gain_chips``: idle consolidation must raise the largest
      contiguous free block by at least this many chips (make-room for a
      concretely blocked gang ignores it — the gang itself is the gain).
    - ``max_moves`` / ``window_s``: at most this many gang migrations
      (straggler moves + gangs displaced by a defrag drain) per sliding
      window — the fleet-wide churn ceiling.
    - ``hysteresis_s``: a gang the rescheduler just moved, or a node it
      just defragmented, is untouchable for this long; with the
      scheduler's straggler-flag deprioritization this is what prevents
      A→B→A ping-pong on an oscillating fleet.
    - ``drain_window_s``: the maintenance deadline stamped on a defrag
      victim; generous on purpose — migration happens at adoption, the
      deadline only bounds a wedged drain (escalation is still free).
    """

    def __init__(
        self,
        store,
        recorder: Optional[EventRecorder] = None,
        *,
        interval: float = 2.0,
        node_grace: float = 6.0,
        min_gain_chips: int = 2,
        max_moves: int = 2,
        window_s: float = 60.0,
        hysteresis_s: float = 120.0,
        drain_window_s: float = 60.0,
        cache=None,
    ):
        self.store = store
        self.recorder = recorder
        self.interval = interval
        self.node_grace = node_grace
        self.min_gain_chips = int(min_gain_chips)
        self.max_moves = int(max_moves)
        self.window_s = window_s
        self.hysteresis_s = hysteresis_s
        self.drain_window_s = drain_window_s
        self.cache = cache
        self.read = cache if cache is not None else store
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # sliding-window migration timestamps (one per gang moved)
        self._window: List[float] = []
        # job uid -> last move ts (gang hysteresis)
        self._moved: Dict[str, float] = {}
        # node name -> last defrag ts (node hysteresis)
        self._node_moved: Dict[str, float] = {}
        # in-flight defrag drains: node name -> stamped deadline
        self._defragging: Dict[str, float] = {}
        # park-event dedupe: object key -> last message
        self._last_park: Dict[str, str] = {}

    # -- lifecycle (the house reconciler shape) -----------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="rescheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync()
            except Exception:
                log.exception("rescheduler sync failed; retrying next tick")

    # -- one level-triggered pass -------------------------------------------

    def sync(self, now: Optional[float] = None) -> None:
        if self.cache is not None and not self.cache.has_synced():
            return
        # injectable clock: convcheck drives the pass on a VirtualClock
        now = time.time() if now is None else now
        nodes = self.read.list("Node", NODE_NAMESPACE)
        if not nodes:
            return  # scalar 'local' shape: nothing to defragment
        pods = self.read.list("Pod")
        live = self._live_nodes(nodes, now)
        used = GangScheduler._node_used(pods)
        schedulable = [
            n for n in live
            if ANNOTATION_MAINTENANCE_AT not in n.metadata.annotations
        ]
        free = {
            n.metadata.name:
                max(0, (n.status.capacity_chips or 0)
                    - used.get(n.metadata.name, 0))
            for n in schedulable
        }
        metrics.fleet_free_chips.set(sum(free.values()))
        metrics.schedulable_contiguous_chips.set(max(free.values(), default=0))

        self._complete_defrags(nodes, pods, now)
        self._prune(now)

        jobs = self.read.list("TPUJob")
        jobs_by_key = {
            (j.metadata.namespace, j.metadata.name): j for j in jobs
        }
        parked = 0
        parked += self._straggler_pass(jobs, pods, nodes, schedulable,
                                       used, now)
        parked += self._defrag_pass(live, schedulable, free, used, pods,
                                    jobs_by_key, now)
        metrics.rescheduler_parked.set(parked)

    # -- straggler moves ----------------------------------------------------

    def _straggler_pass(self, jobs, pods, nodes, schedulable, used,
                        now: float) -> int:
        parked = 0
        node_by_name = {n.metadata.name: n for n in nodes}
        for job in sorted(jobs, key=lambda j: (j.metadata.namespace,
                                               j.metadata.name)):
            if not has_condition(job.status, ConditionType.STRAGGLER):
                continue
            blob = job.status.train_telemetry or {}
            who = blob.get("straggler") or ""
            if "@" not in who:
                continue  # condition set but rollup not landed yet
            pod_key, node_name = who.rsplit("@", 1)
            if has_condition(job.status, ConditionType.MIGRATING) or \
                    has_condition(job.status, ConditionType.RESTARTING):
                continue  # a teardown is already in flight: never a second
            node = node_by_name.get(node_name)
            if node is None or \
                    ANNOTATION_MAINTENANCE_AT in node.metadata.annotations:
                continue  # gone or already draining: the drain plane owns it
            uid = job.metadata.uid
            last = self._moved.get(uid)
            if last is not None and now - last < self.hysteresis_s:
                # the message must be tick-stable (keyed on the MOVE time,
                # not the elapsed time): _park dedupes on message equality,
                # and a message embedding "Ns ago" changes every sync —
                # one Event per tick, forever, on an otherwise-idle cluster
                parked += self._park(
                    job,
                    f"straggler move parked: gang moved at t={last:.0f} "
                    f"(hysteresis {self.hysteresis_s:.0f}s)",
                )
                continue
            ns, gang = job.metadata.namespace, job.metadata.name
            members = self._gang_pods(pods, ns, gang)
            if not members:
                continue
            # the move is only a move if the gang can land somewhere that
            # is not the sick host: simulate on clean nodes excluding it
            cand = [n for n in schedulable if n.metadata.name != node_name]
            scratch = self._without_gangs(used, pods, {(ns, gang)})
            costs = [pod_cost(p) for p in
                     sorted(members, key=lambda p: p.metadata.name)]
            if not self._place(cand, scratch, costs):
                parked += self._park(
                    job,
                    f"straggler move parked: no alternative placement for "
                    f"the gang off {node_name}",
                )
                continue
            if len(self._window) >= self.max_moves:
                parked += self._park(
                    job,
                    f"straggler move parked: migration cap "
                    f"({self.max_moves}/{self.window_s:.0f}s) exhausted",
                )
                continue
            self._flag_node(node_name, now)
            n = self._migrate_gang(
                ns, gang, members,
                f"straggler {pod_key} on {node_name}: gang rescheduled "
                f"off suspected-slow hardware (free checkpoint-then-"
                f"migrate)",
            )
            if n:
                self._moved[uid] = now
                self._window.append(now)
                metrics.reschedules_total.inc(outcome="straggler_move")
                if self.recorder is not None:
                    self.recorder.event(
                        job, NORMAL, EVENT_RESCHEDULED,
                        f"gang {ns}/{gang}: {n} pod(s) migrating off "
                        f"straggler-flagged node {node_name}",
                    )
        return parked

    def _flag_node(self, name: str, now: float) -> None:
        try:
            self.store.patch(
                "Node", NODE_NAMESPACE, name,
                {"metadata": {"annotations": {
                    ANNOTATION_STRAGGLER_NODE: str(now),
                }}},
            )
        except NotFound:
            pass  # node deregistered under us; the move still helps

    def _migrate_gang(self, ns: str, gang: str, members: List,
                      why: str) -> int:
        """Evict every live member WHOLE through the sanctioned free
        seam (reason=Maintenance: restart_generation advances, never
        restart_count) — the rescheduler's only direct eviction path,
        and an oplint DIS001 sanctioned function."""
        n = 0
        for p in sorted(members, key=lambda p: p.metadata.name):
            if evict_pod(self.store, p, why, reason=REASON_MAINTENANCE):
                n += 1
        return n

    # -- defragmentation ----------------------------------------------------

    def _defrag_pass(self, live, schedulable, free, used, pods,
                     jobs_by_key, now: float) -> int:
        parked = 0
        blocked = self._blocked_gangs(live, schedulable, free, used, pods)
        if self._defragging:
            return parked  # one drain in flight: let it land first
        budget = self.max_moves - len(self._window)
        if blocked and budget <= 0:
            ns, gang, costs, members = blocked[0]
            return parked + self._park(
                members[0],
                f"defrag parked: gang {ns}/{gang} ({sum(costs)} chips) is "
                f"fragmentation-blocked but the migration cap "
                f"({self.max_moves}/{self.window_s:.0f}s) is exhausted",
            )
        if budget <= 0:
            return parked
        plan = self._plan_defrag(live, schedulable, free, used, pods,
                                 jobs_by_key, blocked, budget, now)
        if plan is None:
            if blocked:
                ns, gang, costs, members = blocked[0]
                parked += self._park(
                    members[0],
                    f"fleet fragmented: gang {ns}/{gang} ({sum(costs)} "
                    f"chips) fits total-free ({sum(free.values())}) but "
                    f"not contiguous-free "
                    f"({max(free.values(), default=0)}), and no defrag "
                    f"plan satisfies governance",
                )
            return parked
        victim, gangs, moved_chips, reason = plan
        name = victim.metadata.name
        deadline = now + self.drain_window_s
        try:
            self.store.patch(
                "Node", NODE_NAMESPACE, name,
                {"metadata": {"annotations": {
                    ANNOTATION_MAINTENANCE_AT: str(deadline),
                }}},
            )
        except NotFound:
            return parked  # deregistered between snapshot and act
        self._defragging[name] = deadline
        self._node_moved[name] = now
        for key in gangs:
            job = jobs_by_key.get(key)
            if job is not None:
                self._moved[job.metadata.uid] = now
            self._window.append(now)
        metrics.reschedules_total.inc(outcome="defrag_drain")
        log.info("defrag: draining %s (%d gang(s), %d chips): %s",
                 name, len(gangs), moved_chips, reason)
        if self.recorder is not None:
            self.recorder.event(
                victim, NORMAL, EVENT_DEFRAG_DRAINING,
                f"defrag: maintenance window stamped "
                f"(+{self.drain_window_s:.0f}s) to consolidate "
                f"{len(gangs)} gang(s) ({moved_chips} chips) elsewhere — "
                f"{reason}",
            )
        return parked

    def _blocked_gangs(self, live, schedulable, free, used, pods):
        """Queued gangs that fit the fleet's TOTAL free chips but have no
        placement — pure fragmentation casualties, the make-room
        trigger (also `ctl top --fragmentation`'s exit-1 condition)."""
        pending: Dict[Tuple[str, str], List] = {}
        for p in pods:
            if p.spec.node_name or p.is_finished():
                continue
            gang = p.metadata.labels.get(LABEL_JOB_NAME)
            if gang and LABEL_SERVE_NAME not in p.metadata.labels:
                pending.setdefault((p.metadata.namespace, gang),
                                   []).append(p)
        out = []
        total_free = sum(free.values())
        for (ns, gang), members in sorted(pending.items()):
            members.sort(key=lambda p: p.metadata.name)
            costs = [pod_cost(p) for p in members]
            if sum(costs) > total_free:
                continue  # genuinely out of capacity: not our problem
            if self._place(live, dict(used), costs):
                continue  # placeable: the scheduler just hasn't yet
            out.append((ns, gang, costs, members))
        return out

    def _plan_defrag(self, live, schedulable, free, used, pods,
                     jobs_by_key, blocked, budget: int, now: float):
        """Pick the cheapest victim node whose whole-gang evacuation (a)
        is re-placeable on the rest of the fleet, (b) either unblocks a
        fragmentation-blocked gang or raises the contiguous block by
        >= min_gain_chips, and (c) fits the remaining migration budget.
        Returns (victim, gang keys, moved chips, reason) or None."""
        cur_contig = max(free.values(), default=0)
        best = None
        for victim in sorted(schedulable, key=lambda n: n.metadata.name):
            name = victim.metadata.name
            last = self._node_moved.get(name)
            if last is not None and now - last < self.hysteresis_s:
                continue
            vpods = [p for p in pods
                     if p.spec.node_name == name and not p.is_finished()]
            if not vpods:
                continue  # already a clean block
            if any(LABEL_SERVE_NAME in p.metadata.labels for p in vpods):
                continue  # serve hosts are out of scope (budget safety)
            gangs = set()
            movable = True
            for p in vpods:
                gang = p.metadata.labels.get(LABEL_JOB_NAME)
                if not gang:
                    movable = False
                    break
                gangs.add((p.metadata.namespace, gang))
            if not movable or len(gangs) > budget:
                continue
            for key in gangs:
                job = jobs_by_key.get(key)
                if job is None or \
                        has_condition(job.status, ConditionType.MIGRATING) or \
                        has_condition(job.status, ConditionType.RESTARTING) or \
                        (job.metadata.uid in self._moved
                         and now - self._moved[job.metadata.uid]
                         < self.hysteresis_s):
                    movable = False
                    break
            if not movable:
                continue
            # simulate: whole gangs leave (members anywhere — an XLA gang
            # moves together), then must re-place off the victim
            others = [n for n in schedulable if n.metadata.name != name]
            scratch = self._without_gangs(used, pods, gangs)
            moved_chips = sum(used.values()) - sum(scratch.values())
            ok = True
            for key in sorted(gangs):
                members = self._gang_pods(pods, *key)
                costs = [pod_cost(p) for p in
                         sorted(members, key=lambda p: p.metadata.name)]
                if not self._place(others, scratch, costs):
                    ok = False
                    break
            if not ok:
                continue
            if blocked:
                # make-room: after the drain the victim is a clean block
                # again — the blocked gang must then fit the fleet
                sim_nodes = others + [victim]
                sim = dict(scratch)
                sim[name] = 0
                ns, gang, costs, _members = blocked[0]
                if not self._place(sim_nodes, sim, costs):
                    continue
                reason = (f"makes room for fragmentation-blocked gang "
                          f"{ns}/{gang} ({sum(costs)} chips)")
            else:
                cap = victim.status.capacity_chips or 0
                proj = max(
                    [cap] + [
                        max(0, (n.status.capacity_chips or 0)
                            - scratch.get(n.metadata.name, 0))
                        for n in others
                    ]
                )
                if proj - cur_contig < self.min_gain_chips:
                    continue
                reason = (f"raises the contiguous free block "
                          f"{cur_contig} -> {proj} chips")
            score = (moved_chips, name)
            if best is None or score < best[0]:
                best = (score, victim, gangs, moved_chips, reason)
        if best is None:
            return None
        _score, victim, gangs, moved_chips, reason = best
        return victim, gangs, moved_chips, reason

    def _complete_defrags(self, nodes, pods, now: float) -> None:
        """Finish in-flight defrag drains: once the victim is empty,
        clear the maintenance stamp and uncordon — the whole point was
        returning the node to the pool as one contiguous block (a real
        maintenance drain, by contrast, stays cordoned until `ctl
        uncordon`: that hardware actually leaves)."""
        node_by_name = {n.metadata.name: n for n in nodes}
        for name in sorted(self._defragging):
            node = node_by_name.get(name)
            if node is None or \
                    ANNOTATION_MAINTENANCE_AT not in node.metadata.annotations:
                # deregistered, or an operator uncordoned it under us:
                # either way the drain is no longer ours to complete
                del self._defragging[name]
                continue
            if any(p.spec.node_name == name and not p.is_finished()
                   for p in pods):
                continue  # evacuation still in flight
            try:
                self.store.patch(
                    "Node", NODE_NAMESPACE, name,
                    {"metadata": {"annotations": {
                        ANNOTATION_MAINTENANCE_AT: None,
                    }}},
                )
                self.store.patch(
                    "Node", NODE_NAMESPACE, name,
                    {"status": {"unschedulable": False}},
                    subresource="status",
                )
            except NotFound:
                del self._defragging[name]
                continue
            del self._defragging[name]
            metrics.reschedules_total.inc(outcome="defrag_complete")
            log.info("defrag: %s empty; uncordoned (one clean block back "
                     "in the pool)", name)
            if self.recorder is not None:
                self.recorder.event(
                    node, NORMAL, EVENT_DEFRAG_COMPLETE,
                    f"defrag complete: {name} evacuated and uncordoned — "
                    f"its full chip block is schedulable again",
                )

    # -- shared helpers -----------------------------------------------------

    def _live_nodes(self, all_nodes, now: float) -> List:
        out = []
        for n in all_nodes:
            if not n.status.ready or n.status.unschedulable:
                continue
            hb = n.status.last_heartbeat
            if hb and now - hb > self.node_grace:
                continue
            out.append(n)
        return sorted(out, key=lambda n: n.metadata.name)

    @staticmethod
    def _gang_pods(pods, ns: str, gang: str) -> List:
        return [
            p for p in pods
            if p.metadata.namespace == ns
            and p.metadata.labels.get(LABEL_JOB_NAME) == gang
            and not p.is_finished()
        ]

    @staticmethod
    def _without_gangs(used: Dict[str, int], pods,
                       gangs) -> Dict[str, int]:
        """Usage snapshot with the named gangs' live members removed
        fleet-wide (whole-gang semantics: members off the victim node
        move too)."""
        scratch = dict(used)
        for p in pods:
            if p.is_finished() or not p.spec.node_name:
                continue
            key = (p.metadata.namespace,
                   p.metadata.labels.get(LABEL_JOB_NAME))
            if key in gangs:
                scratch[p.spec.node_name] = max(
                    0, scratch.get(p.spec.node_name, 0) - pod_cost(p)
                )
        return scratch

    @staticmethod
    def _place(nodes, scratch: Dict[str, int], costs: List[int]) -> bool:
        """Greedy placement sim using the scheduler's OWN tiered pick
        (gang.py) so the plan and the eventual real placement cannot
        disagree on feasibility; mutates scratch, True iff all fit."""
        for c in costs:
            target = GangScheduler._pick_node(nodes, scratch, c)
            if target is None:
                return False
            scratch[target] = scratch.get(target, 0) + c
        return True

    def _prune(self, now: float) -> None:
        self._window = [t for t in self._window
                        if now - t < self.window_s]
        for d in (self._moved, self._node_moved):
            for k in [k for k, t in d.items()
                      if now - t > self.hysteresis_s]:
                del d[k]
        if len(self._last_park) > 4096:
            self._last_park.clear()

    def _park(self, obj, message: str) -> int:
        """Explaining Event for a governance-parked move, deduped per
        object until the message changes. Returns 1 (the parked count
        contribution) so call sites read additively."""
        key = f"{obj.metadata.namespace}/{obj.metadata.name}"
        if self._last_park.get(key) != message:
            self._last_park[key] = message
            log.info("parked: %s: %s", key, message)
            if self.recorder is not None:
                self.recorder.event(obj, WARNING, EVENT_PARKED, message)
        return 1


def smoke() -> int:
    """The <30s rescheduler smoke (verify SKILL.md static gate): three
    2-chip filler gangs spread across a 3-node/4-chip hollow fleet, then
    a 4-chip gang that fits total-free (6) but no single node — the
    make-room path must stamp a defrag drain, the disruption plane must
    migrate the victim's gang for free, and the rescheduler must
    uncordon the emptied node so the blocked gang binds. Bars: the big
    gang runs, zero restart_count burned anywhere, a DefragComplete
    Event landed, and the victim is back in service (no maintenance
    stamp, schedulable). One JSON line; exit 0 iff all hold."""
    import json

    from mpi_operator_tpu.api.client import TPUJobClient
    from mpi_operator_tpu.controller.controller import TPUJobController
    from mpi_operator_tpu.controller.disruption import DrainController
    from mpi_operator_tpu.executor.hollow import HollowFleet, HollowTimeline
    from mpi_operator_tpu.machinery.store import ObjectStore

    t0 = time.time()
    store = ObjectStore()
    recorder = EventRecorder(store)
    client = TPUJobClient(store)
    ctrl = TPUJobController(store, recorder)
    sched = GangScheduler(store, recorder)
    drain = DrainController(store, recorder, interval=0.1)
    # min_gain_chips=4 keeps idle consolidation quiet so the smoke
    # exercises the make-room trigger specifically
    resched = Rescheduler(
        store, recorder, interval=0.2, min_gain_chips=4, max_moves=4,
        window_s=60.0, hysteresis_s=5.0, drain_window_s=20.0,
    )
    fleet = HollowFleet(
        store, 3, timeline=HollowTimeline(run_s=120.0),
        capacity_chips=4, heartbeat_interval=0.5,
    )
    out = {"metric": "rescheduler_smoke", "ok": False}

    def create(name: str, chips: int) -> None:
        client.create({
            "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "slots_per_worker": chips,
                "slice": {"accelerator": "cpu", "chips_per_host": chips},
                "run_policy": {"clean_pod_policy": "None"},
                "worker": {"replicas": 1, "template": {
                    "containers": [{"image": "smoke/noop",
                                    "command": ["true"]}],
                }},
            },
        })

    def wait(fn, timeout: float, what: str):
        deadline = time.time() + timeout
        while time.time() < deadline:
            v = fn()
            if v:
                return v
            time.sleep(0.1)
        raise RuntimeError(f"timed out waiting for {what}")

    def bound_nodes(job: str):
        return {
            p.spec.node_name
            for p in store.list("Pod", "default")
            if p.metadata.labels.get(LABEL_JOB_NAME) == job
            and p.spec.node_name and not p.is_finished()
        }

    try:
        ctrl.run()
        sched.start()
        fleet.start()
        drain.start()
        wait(lambda: len(store.list("Node", NODE_NAMESPACE)) == 3,
             10.0, "fleet registration")
        # sequential creates pin the spread: one 2-chip gang per node
        for i in range(3):
            create(f"frag-{i}", 2)
            wait(lambda i=i: bound_nodes(f"frag-{i}"), 10.0,
                 f"frag-{i} binding")
        create("big", 4)
        resched.start()
        big_nodes = wait(lambda: bound_nodes("big"), 25.0,
                         "the blocked gang binding after defrag")
        wait(lambda: all(
            p.status.phase == "Running"
            for p in store.list("Pod", "default")
            if p.metadata.labels.get(LABEL_JOB_NAME) == "big"
        ), 10.0, "the blocked gang running")
        burned = sum(
            j.status.restart_count or 0
            for j in store.list("TPUJob", "default")
        )
        completes = [
            e for e in store.list("Event", NODE_NAMESPACE)
            if e.reason == EVENT_DEFRAG_COMPLETE
        ]
        victim = completes[0].involved.name if completes else None
        victim_ok = False
        if victim:
            n = store.get("Node", NODE_NAMESPACE, victim)
            victim_ok = (
                ANNOTATION_MAINTENANCE_AT not in n.metadata.annotations
                and not n.status.unschedulable
            )
        out.update({
            "big_bound_on": sorted(big_nodes),
            "burned_restarts": burned,
            "defrag_completes": len(completes),
            "victim": victim,
            "victim_back_in_service": victim_ok,
            "elapsed_s": round(time.time() - t0, 1),
        })
        out["ok"] = bool(
            big_nodes and burned == 0 and completes and victim_ok
            and not resched._defragging
        )
    except Exception as e:
        log.exception("rescheduler smoke failed")
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        resched.stop()
        drain.stop()
        fleet.stop()
        sched.stop()
        ctrl.stop()
    print(json.dumps(out))
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m mpi_operator_tpu.controller.rescheduler",
        description=__doc__,
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the in-process defrag make-room smoke "
                         "(one JSON line; exit 0 iff it held)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.smoke:
        return smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
