"""DrainController: the disruption plane's per-node evacuation orchestrator.

Real TPU fleets are dominated by *planned* disruption — Cloud TPU
maintenance events and spot reclaims arrive with advance notice — yet until
this module the operator only had the unplanned path (node_monitor sees a
dead heartbeat and fires a lossy gang restart). This controller turns
"this node will die at T" into a budgeted, observable workflow:

- **Notice contract**: a Node carrying the ``tpujob.dev/maintenance-at``
  annotation (absolute unix ts — stamped by ``ctl drain <node>
  [--deadline S]`` or a hollow fleet's seeded maintenance schedule) is
  adopted: cordoned (no new bindings) and marked with an active
  ``Draining`` condition.
- **Batch gangs get checkpoint-then-migrate**: every TPUJob gang with a
  member bound to the draining node is evicted WHOLE (reason
  ``Maintenance``) — the agent's ``--eviction-grace`` path SIGTERMs each
  worker, which force-checkpoints at a gang-uniform step (ops/elastic.py)
  before exiting; the controller then relaunches the full gang, which the
  scheduler places off the cordoned node. The move is FREE:
  ``restart_generation`` advances, ``restart_count`` (the backoffLimit
  budget) does not.
- **Serve replicas migrate surge-first**: the TPUServe controller (made
  drain-aware in controller/serve.py) surges a replacement gang elsewhere,
  waits for it to pass the readiness gate, and only then retires the
  doomed replica — ``ready_total`` never drops below the serve's
  ``DisruptionBudget``. This controller only *observes* serve progress: a
  drain that cannot proceed without violating a budget (cluster too full
  to surge) parks as ``drain_budget_blocked`` with an Event explaining
  why, and unblocks the moment capacity frees (everything here is
  level-triggered — no internal state a failover could lose).
- **Deadline escalation**: when ``maintenance-at`` arrives (or the node is
  already dead — a draining node that also stops heartbeating resolves to
  ONE eviction, here, never a second one in node_monitor) anything still
  bound is hard-evicted: the budget yields to physics, because the
  hardware is going away either way.
- **Failover-safe by construction**: the notice, the cordon, the Draining
  condition and every eviction live in the store; the per-tick sync
  re-derives everything else, so a new leader resumes a half-finished
  drain exactly where the old one died.

Observability: ``drain.node`` (one per adopted notice) → per-gang
``drain.migrate_gang`` spans in each affected job's trace (the cross-trace
edge ``ctl trace`` renders), ``drain.escalate`` on deadline overruns;
``tpu_operator_drains_total`` by outcome, the ``drain_budget_blocked``
gauge, and the ``drain_migration_latency`` histogram — sampled every tick
for still-draining nodes past the SLO threshold, so a STUCK drain keeps
scoring bad events and the burn-rate monitor pages (the
``drain-migration`` objective in controller/slo_defaults.json).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.events import NORMAL, WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    NODE_NAMESPACE,
    REASON_MAINTENANCE,
    NodeConditionType,
    evict_pod,
    maintenance_at,
    node_draining,
)
from mpi_operator_tpu.machinery.store import NotFound
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.drain")

# duplicated label constants (this controller must not import the batch or
# serve controller modules just for strings; tests pin they stay identical)
LABEL_JOB_NAME = "tpujob.dev/job-name"
LABEL_SERVE_NAME = "tpujob.dev/serve-name"

EVENT_DRAIN_STARTED = "DrainStarted"
EVENT_DRAIN_COMPLETED = "DrainCompleted"
EVENT_DRAIN_ESCALATED = "DrainEscalated"
EVENT_DRAIN_BLOCKED = "DrainBudgetBlocked"
EVENT_MAINTENANCE_INVALID = "MaintenanceAnnotationInvalid"
EVENT_GANG_MIGRATING = "GangMigrating"

# how a migration-latency "bad event" is scored while a drain is still in
# flight: once the node has been draining longer than this, every tick
# observes the elapsed age into the histogram — a stuck drain therefore
# keeps burning SLO budget until someone acts (see module docstring).
STUCK_SAMPLE_AFTER_S = 60.0


class DrainController:
    """Leader-only, level-triggered per-node evacuation. Same operational
    shape as the NodeMonitor (periodic scan over informer reads, writes
    through the store); every decision is recomputed from observed state,
    which is what makes a half-finished drain survive leader failover."""

    def __init__(
        self,
        store,
        recorder: Optional[EventRecorder] = None,
        *,
        interval: float = 1.0,
        node_grace: float = 6.0,
        cache=None,
    ):
        self.store = store
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(
            store, component="tpujob-drain-controller"
        )
        self.interval = interval
        # a draining node whose heartbeat is older than this is DEAD: the
        # grace window cannot checkpoint anything, so escalation fires
        # immediately (matches the NodeMonitor's liveness bar)
        self.node_grace = node_grace
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # node name → (maintenance_at value, drain.node span context,
        # first-seen ts): the trace anchor of the current drain. Rebuilt
        # lazily after failover — a fresh leader opens a fresh drain.node
        # span; causality still connects through the migrate spans in each
        # job's trace.
        self._active: Dict[str, Tuple[float, object, float]] = {}
        # (job uid, restart_generation) pairs already migrated — the
        # once-per-generation guard on migrate spans/events (evict_pod
        # itself is idempotent; this only dedupes observability)
        self._migrated: Set[Tuple[str, int]] = set()
        # node → last blocked-explanation message (Event dedupe)
        self._blocked_msg: Dict[str, str] = {}
        # node → deadline of the drain already recorded COMPLETE: the
        # Drained patch goes through self.store but the next tick re-reads
        # through the informer, which may not have echoed it yet — without
        # this memo that one stale read double-counts drains_total
        # {completed}, double-observes the latency histogram and re-emits
        # the DrainCompleted event
        self._completed: Dict[str, float] = {}
        # nodes whose malformed annotation was already warned about
        self._warned_invalid: Set[str] = set()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="drain-controller", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync()
            except Exception:
                log.exception("drain sync failed")  # next tick retries

    # -- the per-tick evacuation pass ---------------------------------------

    def sync(self, now: Optional[float] = None) -> None:
        if self.cache is not None and not self.cache.has_synced():
            return  # cold cache = empty world; next tick retries
        # injectable clock: convcheck drives the pass on a VirtualClock
        now = time.time() if now is None else now
        nodes = self.read.list("Node", NODE_NAMESPACE)
        noticed = {}
        for node in nodes:
            if ANNOTATION_MAINTENANCE_AT not in node.metadata.annotations:
                # a completed drain's bookkeeping is dropped when the
                # annotation clears (ctl uncordon after maintenance)
                self._forget(node.metadata.name)
                continue
            deadline = maintenance_at(node)
            if deadline is None:
                if node.metadata.name not in self._warned_invalid:
                    self._warned_invalid.add(node.metadata.name)
                    self.recorder.event(
                        node, WARNING, EVENT_MAINTENANCE_INVALID,
                        f"unparseable {ANNOTATION_MAINTENANCE_AT} value "
                        f"{node.metadata.annotations.get(ANNOTATION_MAINTENANCE_AT)!r}"
                        f" — expected a unix timestamp; ignoring the notice",
                    )
                continue
            noticed[node.metadata.name] = (node, deadline)
        for stale in set(self._active) - set(noticed):
            self._forget(stale)
        if not noticed:
            metrics.drain_budget_blocked.set(0)
            return

        # ONE pod list for the whole tick regardless of draining-node count
        pods = self.read.list("Pod")
        blocked_total = 0
        for name, (node, deadline) in sorted(noticed.items()):
            try:
                blocked_total += self._sync_node(node, deadline, pods, now)
            except NotFound:
                continue  # node deleted under us; next tick re-derives
        metrics.drain_budget_blocked.set(blocked_total)

    def _forget(self, node_name: str) -> None:
        self._active.pop(node_name, None)
        self._blocked_msg.pop(node_name, None)
        self._completed.pop(node_name, None)
        self._warned_invalid.discard(node_name)

    def _sync_node(self, node, deadline: float, pods: List, now: float) -> int:
        """Evacuate one noticed node. Returns the number of budget-blocked
        serves currently parking this drain (the gauge contribution)."""
        name = node.metadata.name
        live = [
            p for p in pods
            if p.spec.node_name == name and not p.is_finished()
        ]
        anchor = self._adopt(node, deadline, now, idle=not live)
        if not live:
            self._complete(node, anchor, now)
            return 0
        age = now - anchor[2]
        if age > STUCK_SAMPLE_AFTER_S:
            # a stuck drain must PAGE: keep scoring its age as a bad
            # latency event so the burn-rate monitor sees a breach (a
            # completed drain scores its true latency exactly once)
            metrics.drain_migration_latency.observe(age)
        hb = node.status.last_heartbeat
        dead = bool(hb) and now - hb > self.node_grace or not node.status.ready
        if now >= deadline or dead:
            self._escalate(node, anchor, live, dead=dead, now=now)
            return 0
        batch = [p for p in live if LABEL_SERVE_NAME not in p.metadata.labels]
        self._migrate_batch_gangs(node, anchor, batch)
        return self._observe_serve_progress(node, live)

    # -- adoption / completion ----------------------------------------------

    def _adopt(self, node, deadline: float, now: float, *,
               idle: bool = False):
        """Idempotently take ownership of a maintenance notice: cordon,
        flip the Draining condition active, open the drain.node anchor
        span. Store state is only written when it differs (a resumed
        leader re-adopts for free); the in-memory anchor re-arms whenever
        the maintenance-at value changes (a re-scheduled window is a new
        drain). ``idle`` (no live pod bound) adoption never touches the
        Draining condition: re-activating it on a node whose drain a
        PREVIOUS leader already completed — or that was empty all along —
        would re-announce a drain with nothing to do and strand the
        condition active."""
        name = node.metadata.name
        cur = self._active.get(name)
        if cur is not None and cur[0] == deadline:
            return cur
        with trace.start_span(
            "drain.node",
            attrs={
                "node": name,
                "maintenance_at": deadline,
                "notice_s": round(max(0.0, deadline - now), 1),
            },
        ) as sp:
            anchor = (deadline, sp.context(), now)
            self._active[name] = anchor
            self._completed.pop(name, None)  # a new window drains anew
            changes = {}
            if not node.status.unschedulable:
                changes["unschedulable"] = True
            if not idle and not node_draining(node):
                changes["conditions"] = self._conditions_patch(
                    node, True, "MaintenanceNotice",
                    f"maintenance at {deadline:.0f}; evacuating",
                )
            if changes:
                try:
                    self.store.patch(
                        "Node", NODE_NAMESPACE, name,
                        {"status": changes}, subresource="status",
                    )
                except NotFound:
                    raise
                self.recorder.event(
                    node, NORMAL, EVENT_DRAIN_STARTED,
                    f"maintenance notice adopted: node dies at "
                    f"{deadline:.0f} ({max(0.0, deadline - now):.0f}s); "
                    f"cordoned, evacuating",
                )
                metrics.drains_total.inc(outcome="started")
        return anchor

    @staticmethod
    def _conditions_patch(node, active: bool, reason: str,
                          message: str) -> List[dict]:
        """The full conditions list with Draining set as asked — Node
        conditions ride a merge patch, and lists replace whole."""
        from mpi_operator_tpu.api.types import Condition

        out = [
            c.to_dict() for c in node.status.conditions
            if c.type != NodeConditionType.DRAINING
        ]
        out.append(Condition.new(
            NodeConditionType.DRAINING, active, reason, message
        ).to_dict())
        return out

    def _complete(self, node, anchor, now: float) -> None:
        """Nothing live remains bound: the drain is done. The node stays
        cordoned and keeps its notice (the hardware still dies at T);
        `ctl uncordon` clears both when it returns from maintenance."""
        if self._completed.get(node.metadata.name) == anchor[0]:
            return  # recorded; an informer read lagging our own Drained
            # patch must not double-count the completion
        if not node_draining(node):
            # already inactive in the store (e.g. a resumed leader finds
            # the predecessor's bookkeeping finished): memo and move on
            self._completed[node.metadata.name] = anchor[0]
            return
        latency = now - anchor[2]
        with trace.start_span(
            "drain.node_complete", parent=anchor[1],
            attrs={"node": node.metadata.name,
                   "drain_latency_s": round(latency, 3)},
        ):
            try:
                self.store.patch(
                    "Node", NODE_NAMESPACE, node.metadata.name,
                    {"status": {"conditions": self._conditions_patch(
                        node, False, "Drained",
                        f"node empty after {latency:.1f}s",
                    )}},
                    subresource="status",
                )
            except NotFound:
                return
        self._completed[node.metadata.name] = anchor[0]
        metrics.drain_migration_latency.observe(latency)
        metrics.drains_total.inc(outcome="completed")
        self.recorder.event(
            node, NORMAL, EVENT_DRAIN_COMPLETED,
            f"drain complete in {latency:.1f}s; node empty and cordoned "
            f"until `ctl uncordon`",
        )
        self._blocked_msg.pop(node.metadata.name, None)

    # -- batch: checkpoint-then-migrate -------------------------------------

    def _migrate_batch_gangs(self, node, anchor, batch: List) -> None:
        """Evict every affected batch gang WHOLE (reason=Maintenance): the
        agent SIGTERMs each member (--eviction-grace force-checkpoint), the
        controller advances restart_generation (NOT restart_count — a
        planned move is free) and the scheduler re-places the relaunched
        gang off the cordoned node."""
        by_gang: Dict[Tuple[str, str], List] = {}
        for p in batch:
            gang = p.metadata.labels.get(LABEL_JOB_NAME)
            if gang:
                by_gang.setdefault((p.metadata.namespace, gang), []).append(p)
        if not by_gang:
            return
        # gang members NOT on the draining node are collateral: the whole
        # gang moves (an XLA gang cannot lose one member and live), so the
        # eviction covers every live member wherever it is bound
        all_pods = None
        for (ns, gang), members in sorted(by_gang.items()):
            uid_gen = self._gang_identity(members[0])
            if uid_gen is not None and uid_gen in self._migrated:
                continue  # this generation's move is already in flight
            if all_pods is None:
                all_pods = self.read.list("Pod")
            whole = [
                p for p in all_pods
                if p.metadata.namespace == ns
                and p.metadata.labels.get(LABEL_JOB_NAME) == gang
                and not p.is_finished()
            ]
            with trace.start_span(
                "drain.migrate_gang",
                parent=anchor[1],
                trace_id=members[0].metadata.annotations.get(
                    trace.ANNOTATION_TRACE_ID
                ),
                attrs={"node": node.metadata.name, "gang": f"{ns}/{gang}",
                       "members": len(whole)},
            ):
                n = 0
                for p in whole:
                    if evict_pod(
                        self.store, p,
                        f"node {node.metadata.name} draining for "
                        f"maintenance (checkpoint-then-migrate)",
                        reason=REASON_MAINTENANCE,
                    ):
                        n += 1
                if n and uid_gen is not None:
                    self._migrated.add(uid_gen)
                    if len(self._migrated) > 8192:
                        self._migrated.clear()  # bounded; re-evict no-ops
                if n:
                    self.recorder.event(
                        members[0], NORMAL, EVENT_GANG_MIGRATING,
                        f"gang {gang}: {n} pod(s) evicted for maintenance "
                        f"on {node.metadata.name}; checkpoint-then-migrate "
                        f"(free restart)",
                    )
                    metrics.drains_total.inc(outcome="gang_migrated")

    def _gang_identity(self, pod) -> Optional[Tuple[str, str]]:
        """(owner uid, generation label) — the once-per-generation key."""
        owner = next(
            (r for r in pod.metadata.owner_references if r.controller), None
        )
        gen = pod.metadata.labels.get("tpujob.dev/generation", "0")
        if owner is None:
            return None
        return (owner.uid, gen)

    # -- serve: observe surge-first migration / budget parking --------------

    def _observe_serve_progress(self, node, live: List) -> int:
        """The serve controller owns serve migration (surge-first, budget-
        floored); this controller reports blocked budgets. Returns the
        count of serves currently parking this node's drain."""
        serve_names = {
            (p.metadata.namespace,
             p.metadata.labels.get(LABEL_SERVE_NAME))
            for p in live
            if LABEL_SERVE_NAME in p.metadata.labels
        }
        blocked = 0
        msgs = []
        for ns, sname in sorted(serve_names):
            serve = self.read.try_get("TPUServe", ns, sname)
            if serve is None:
                continue
            reason = self._serve_blocked_reason(serve)
            if reason:
                blocked += 1
                msgs.append(f"{ns}/{sname}: {reason}")
        msg = "; ".join(msgs)
        if msg and self._blocked_msg.get(node.metadata.name) != msg:
            self._blocked_msg[node.metadata.name] = msg
            self.recorder.event(
                node, WARNING, EVENT_DRAIN_BLOCKED,
                f"drain parked by disruption budget — {msg}; will resume "
                f"the moment a surged replacement passes readiness (or "
                f"escalate at the maintenance deadline)",
            )
        elif not msg:
            self._blocked_msg.pop(node.metadata.name, None)
        return blocked

    @staticmethod
    def _serve_blocked_reason(serve) -> Optional[str]:
        """Why this serve cannot give up a ready replica right now, or None
        when the migration can proceed (the SAME effective-budget rule the
        serve controller's retire gate applies — one shared helper, so the
        gauge and the gate can never disagree)."""
        from mpi_operator_tpu.api.defaults import (
            effective_disruption_budget,
            set_serve_defaults,
        )

        set_serve_defaults(serve)
        desired = serve.spec.replicas or 0
        # the retire gate's exact floor: the rollout guarantee
        # (desired - max_unavailable) never relaxes, the budget can only
        # tighten it — mirrored from the serve controller's drain loop
        floor = max(desired - (serve.spec.max_unavailable or 0),
                    effective_disruption_budget(serve))
        ready = serve.status.ready_replicas
        if ready - 1 >= floor:
            return None
        return (
            f"ready {ready} - 1 < disruption budget {floor} "
            f"(waiting for a surged replacement to become ready)"
        )

    # -- deadline escalation -------------------------------------------------

    def _escalate(self, node, anchor, live: List, *, dead: bool,
                  now: float) -> None:
        """The maintenance window arrived (or the node already died):
        hard-evict everything still bound. Budgets yield — the hardware is
        going away either way; serve self-healing replaces the gangs after
        the fact. Still reason=Maintenance: the workload being moved did
        nothing wrong, so the restart stays free."""
        why = ("node died while draining" if dead
               else "maintenance deadline reached")
        with trace.start_span(
            "drain.escalate", parent=anchor[1],
            attrs={"node": node.metadata.name, "pods": len(live),
                   "dead": dead,
                   "overrun_s": round(max(0.0, now - anchor[0]), 1)},
        ):
            n = 0
            for p in live:
                with trace.start_span(
                    "drain.hard_evict",
                    trace_id=p.metadata.annotations.get(
                        trace.ANNOTATION_TRACE_ID
                    ),
                    attrs={"pod": p.metadata.key(),
                           "node": node.metadata.name},
                ):
                    if evict_pod(
                        self.store, p,
                        f"hard-evicted: {why} on {node.metadata.name}",
                        reason=REASON_MAINTENANCE,
                    ):
                        n += 1
            if n:
                metrics.drains_total.inc(outcome="escalated")
                self.recorder.event(
                    node, WARNING, EVENT_DRAIN_ESCALATED,
                    f"{why}: {n} pod(s) still bound were hard-evicted "
                    f"(budget yields to the deadline)",
                )


def smoke() -> int:
    """The <30s drain smoke (verify SKILL.md static gate): one hollow node
    drained out from under a 2-replica serve with DisruptionBudget 1 AND a
    running batch gang. Bars: the batch job Succeeds with restart_count 0
    (restart_generation 1 — the move was free), serve ready never dips
    below the budget, the node drains empty (Draining → Drained), and the
    migrated pods land off-node. Prints one JSON line; exit 0 iff all hold.
    """
    import json

    from mpi_operator_tpu.api.client import TPUJobClient, TPUServeClient
    from mpi_operator_tpu.api import conditions as cond
    from mpi_operator_tpu.controller.controller import TPUJobController
    from mpi_operator_tpu.controller.serve import TPUServeController
    from mpi_operator_tpu.executor.hollow import HollowFleet, HollowTimeline
    from mpi_operator_tpu.machinery.store import ObjectStore
    from mpi_operator_tpu.scheduler.gang import GangScheduler

    t0 = time.time()
    store = ObjectStore()
    recorder = EventRecorder(store)
    ctrl = TPUJobController(store, recorder)
    serve_ctrl = TPUServeController(store, recorder)
    sched = GangScheduler(store, recorder)
    drain = DrainController(store, recorder, interval=0.1)
    # TWO nodes, sized so the drain necessarily hits BOTH workload
    # classes: serve replicas spread one per node, batch members too —
    # whichever node hosts batch worker-0 also hosts a serve replica
    fleet = HollowFleet(
        store, 2, timeline=HollowTimeline(run_s=1.5, serve_warmup_s=0.3),
        capacity_chips=6, heartbeat_interval=0.5,
    )
    ctrl.run()
    serve_ctrl.run()
    sched.start()
    fleet.start()
    drain.start()
    out = {"metric": "drain_smoke", "ok": False}
    min_ready = [2]
    try:
        TPUServeClient(store).create({
            "kind": "TPUServe",
            "metadata": {"name": "svc", "namespace": "default"},
            "spec": {"replicas": 2, "workers_per_replica": 1,
                     "slice": {"accelerator": "cpu", "chips_per_host": 2},
                     "disruption_budget": 1, "max_surge": 1},
        })
        TPUJobClient(store).create({
            "kind": "TPUJob", "metadata": {"name": "batch"},
            "spec": {"slice": {"accelerator": "cpu", "chips_per_host": 1},
                     "worker": {"replicas": 2, "template": {"containers": [
                         {"image": "x", "command": ["true"]}]}},
                     "run_policy": {"clean_pod_policy": "None"}}})

        def ready_replicas() -> int:
            s = store.try_get("TPUServe", "default", "svc")
            return s.status.ready_replicas if s else 0

        def wait(fn, timeout, what):
            deadline = time.time() + timeout
            while time.time() < deadline:
                min_ready[0] = min(min_ready[0], ready_replicas())
                if fn():
                    return True
                time.sleep(0.05)
            raise RuntimeError(f"smoke: {what} not reached")

        wait(lambda: ready_replicas() >= 2, 15, "serve ready")
        wait(lambda: any(
            p.spec.node_name and p.status.phase == "Running"
            for p in store.list("Pod", "default")
            if LABEL_SERVE_NAME not in p.metadata.labels
        ), 15, "batch running")
        victim = next(
            p.spec.node_name for p in store.list("Pod", "default")
            if LABEL_SERVE_NAME not in p.metadata.labels
            and p.spec.node_name and not p.is_finished()
        )
        assert any(
            p.spec.node_name == victim
            for p in store.list("Pod", "default")
            if LABEL_SERVE_NAME in p.metadata.labels
        ), "smoke geometry: the victim must host a serve replica too"
        min_ready[0] = 2
        fleet.announce_maintenance(victim, time.time() + 25.0)
        wait(lambda: not any(
            p.spec.node_name == victim and not p.is_finished()
            for p in store.list("Pod")
        ), 20, "node empty")
        wait(lambda: not node_draining(
            store.get("Node", NODE_NAMESPACE, victim)), 10, "drain complete")
        wait(lambda: cond.is_succeeded(
            store.get("TPUJob", "default", "batch").status), 20,
            "batch succeeded")
        wait(lambda: ready_replicas() >= 2, 15, "serve re-ready")
        job = store.get("TPUJob", "default", "batch")
        off_node = all(
            p.spec.node_name != victim
            for p in store.list("Pod") if not p.is_finished()
        )
        out.update({
            "victim": victim,
            "batch_succeeded": bool(cond.is_succeeded(job.status)),
            "restart_count": job.status.restart_count,
            "restart_generation": job.status.restart_generation,
            "min_ready_during_drain": min_ready[0],
            "budget": 1,
            "migrated_off_node": off_node,
            "elapsed_s": round(time.time() - t0, 1),
        })
        out["ok"] = bool(
            out["batch_succeeded"]
            and job.status.restart_count == 0
            and job.status.restart_generation >= 1
            and min_ready[0] >= 1
            and off_node
        )
    except Exception as e:
        log.exception("drain smoke failed")
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        drain.stop()
        fleet.stop()
        sched.stop()
        serve_ctrl.stop()
        ctrl.stop()
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpu-drain",
        description="Disruption-plane utilities (the DrainController "
                    "itself runs leader-only inside tpu-operator).",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the <30s in-process drain smoke: one hollow "
                         "node drained under a 2-replica serve (budget 1) "
                         "+ a batch gang; exit 0 iff every bar holds")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
