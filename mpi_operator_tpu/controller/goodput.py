"""The goodput aggregator: per-job rollup of the workload telemetry plane
(ISSUE 15).

Workers (and hollow timelines) mirror bounded ``train_stats`` blobs into
pod status — cumulative per-incarnation stall buckets + step counters
(runtime/stepstats.py). This controller-side loop rolls them up per job:

- **goodput** = productive step-compute seconds ÷ wall seconds since
  admission. Productive time is the COORDINATOR's ``compute`` bucket (the
  gang is SPMD — summing members would multiply the same seconds), the
  wall clock never stops, and restart downtime — which no worker process
  can observe, being dead — is charged controller-side from the job's
  Restarting/Migrating conditions into the ``restart`` bucket.
- **stall attribution**: per-bucket cumulative seconds + the dominant
  non-compute bucket, written into ``status.train_telemetry`` (a bounded
  blob `ctl top --jobs` renders straight from the store) and observed
  into ``step_latency_seconds{bucket=...}`` as per-step averages.
- **straggler detection**: a gang member whose step p50 exceeds the gang
  median by the skew threshold gets a ``Straggler`` Event + an auxiliary
  job condition naming the exact pod and node; both clear when the skew
  does.
- **restart_to_first_step_seconds**: the outage span from an observed
  gang restart (generation bump, anchored on the restart-ish condition's
  transition time) to the relaunched coordinator's first completed step,
  labeled ``kind=migration|restart`` — the baseline ROADMAP item 5's
  compile-cache work must beat.

Counter resets are absorbed the same way the SLO scraper absorbs process
restarts: a worker blob whose counters DECREASED (new pod incarnation,
relaunched trainer) contributes its post-reset value, never a negative
delta — goodput can only ever move continuously.

Runs leader-only next to the other reconcilers; ``tick()`` is public so
tests, the smoke, and the bench drive it with their own clock.
"""

from __future__ import annotations

import logging
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from mpi_operator_tpu.api import conditions as cond
from mpi_operator_tpu.api.types import ConditionType, TPUJob
from mpi_operator_tpu.controller.controller import (
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
)
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.events import WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    BUCKET_RESTART,
    TRAIN_BUCKETS,
    PodPhase,
)
from mpi_operator_tpu.machinery.store import Conflict, NotFound, ObjectStore
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.goodput")

# a member must have run this many steps this incarnation before its p50
# joins the skew comparison (fresh pods mid-warmup are not stragglers)
SKEW_MIN_STEPS = 3


@dataclass
class _Worker:
    """Last-seen cumulative counters for one pod incarnation — the base
    the next tick's reset-aware deltas are taken against."""

    uid: str
    steps: int = 0
    step: int = 0
    p50_ms: float = 0.0
    buckets: Dict[str, float] = field(default_factory=dict)


@dataclass
class _JobState:
    key: str
    coord_name: str = ""  # TPUJob.worker_name(0): ONE derivation, set once
    admitted_at: Optional[float] = None
    last_tick: Optional[float] = None
    was_running: bool = False
    productive_s: float = 0.0
    steps_total: int = 0
    buckets: Dict[str, float] = field(default_factory=lambda: {
        **{k: 0.0 for k in TRAIN_BUCKETS}, BUCKET_RESTART: 0.0,
    })
    workers: Dict[str, _Worker] = field(default_factory=dict)
    # wall seconds excluded from the goodput denominator (deliberate
    # suspension is an operator action, not lost goodput)
    excluded_s: float = 0.0
    # adoption tick: seed each live worker's delta base from its CURRENT
    # counters instead of charging its whole cumulative again (the
    # telemetry blob we adopted already includes it)
    seed_bases: bool = False
    generation: int = 0
    restart_count_seen: int = 0
    restart_at: Optional[float] = None
    restart_kind: str = ""
    restart_coord_uid: str = ""
    straggler: str = ""          # "<ns>/<pod>@<node>" while skewed
    straggler_uid: str = ""      # pod uid already evented
    telemetry: Optional[Dict[str, Any]] = None  # last written blob


class GoodputAggregator:
    """Roll pod ``train_stats`` up into per-job goodput, stall
    attribution, straggler detection and restart-outage spans."""

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        *,
        cache=None,
        namespace: Optional[str] = None,
        interval: float = 2.0,
        skew_factor: float = 1.5,
        skew_min_ms: float = 1.0,
    ):
        self.store = store
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(
            store, component="tpujob-goodput"
        )
        self.namespace = namespace
        self.interval = interval
        self.skew_factor = skew_factor
        self.skew_min_ms = skew_min_ms
        self._states: Dict[str, _JobState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "GoodputAggregator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpujob-goodput", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                log.exception("goodput tick failed; next tick retries")

    # -- one pass ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        with trace.start_span("goodput.sync"):
            seen = set()
            for job in self.read.list("TPUJob", self.namespace):
                uid = job.metadata.uid
                if cond.is_finished(job.status):
                    self._drop(uid)
                    continue
                seen.add(uid)
                try:
                    self._tick_job(job, now)
                except (Conflict, NotFound):
                    continue  # stale read; next tick re-reads
            for uid in [u for u in self._states if u not in seen]:
                self._drop(uid)
        metrics.goodput_sync_latency.observe(time.perf_counter() - t0)

    def _drop(self, uid: str) -> None:
        state = self._states.pop(uid, None)
        if state is not None:
            # a finished/deleted job's gauges must not export forever
            metrics.job_goodput_ratio.remove(job=state.key)
            metrics.job_stragglers.remove(job=state.key)

    # -- per-job rollup ------------------------------------------------------

    def _tick_job(self, job: TPUJob, now: float) -> None:
        st = self._states.get(job.metadata.uid)
        if st is None:
            # adopt at the job's CURRENT generation: a leader failover
            # picking up a gen-2 job must not read its history as a
            # fresh restart and mint a bogus outage span
            st = self._states[job.metadata.uid] = _JobState(
                job.metadata.key(),
                coord_name=job.worker_name(0),
                generation=job.status.restart_generation,
                restart_count_seen=job.status.restart_count,
            )
            tel = job.status.train_telemetry
            if tel:
                # failover continuity: resume from the PERSISTED rollup —
                # without this, prior incarnations' productive seconds
                # vanish while the wall denominator spans the job's full
                # age, deflating goodput toward the page floor on every
                # operator restart. The live incarnation's contribution
                # is already inside this blob, so its workers' delta
                # bases seed from their current counters (seed_bases).
                b = tel.get("buckets") or {}
                for k in st.buckets:
                    try:
                        st.buckets[k] = float(b.get(k, 0.0) or 0.0)
                    except (TypeError, ValueError):
                        st.buckets[k] = 0.0
                st.productive_s = st.buckets.get("compute", 0.0)
                try:
                    st.steps_total = int(tel.get("steps", 0) or 0)
                except (TypeError, ValueError):
                    st.steps_total = 0
                st.seed_bases = True
        if len(self._states) > 8192:
            self._drop(next(iter(self._states)))
        if st.admitted_at is None:
            st.admitted_at = job.status.start_time or now

        if cond.is_suspended(job.status):
            # deliberate suspension is an operator action, not lost
            # goodput: exclude the window from the wall, stop exporting
            # (a decaying gauge would page goodput-collapse on intent),
            # and charge no downtime. Resume re-exports next tick.
            if st.last_tick is not None:
                st.excluded_s += now - st.last_tick
            st.last_tick = now
            metrics.job_goodput_ratio.remove(job=st.key)
            return

        self._note_restart(job, st, now)
        self._charge_downtime(job, st, now)

        pods = [
            p for p in self.read.list(
                "Pod", job.namespace, selector={LABEL_JOB_NAME: job.name}
            )
            if p.status.phase == PodPhase.RUNNING
        ]
        coord_dsteps = self._ingest_workers(st, pods)
        st.seed_bases = False  # adoption seeding covers ONE tick only
        self._close_restart_span(st, now)
        self._detect_skew(job, st, pods)
        self._write_rollup(job, st, now, coord_dsteps)

    def _note_restart(self, job: TPUJob, st: _JobState, now: float) -> None:
        gen = job.status.restart_generation
        burned = job.status.restart_count > st.restart_count_seen
        st.restart_count_seen = job.status.restart_count
        if gen <= st.generation:
            return
        st.generation = gen
        # kind attribution: an ACTIVE restart-ish condition names the
        # flavor; a relaunch fast enough that Running already replaced it
        # (the condition record is removed, not flipped) falls back to
        # the backoff budget — a generation that burned restart_count is
        # a crash, an unburned one is a planned move (maintenance
        # migration / preemption: the control plane's doing either way)
        anchor, kind = now, ("restart" if burned else "migration")
        for ctype, k in ((ConditionType.MIGRATING, "migration"),
                         (ConditionType.RESTARTING, "restart")):
            c = cond.get_condition(job.status, ctype)
            if c is not None and c.status:
                anchor = min(now, c.last_transition_time or now)
                kind = k
                break
        st.restart_at, st.restart_kind = anchor, kind
        coord = st.workers.get(st.coord_name)
        st.restart_coord_uid = coord.uid if coord else ""

    def _charge_downtime(self, job: TPUJob, st: _JobState,
                         now: float) -> None:
        """Restart downtime, charged controller-side: wall time while a
        restart-ish condition is active — or while a previously-running
        job is not Running (teardown observed before the condition flip).
        Counted between OUR ticks, so resolution is one interval."""
        s = job.status
        down = (
            cond.has_condition(s, ConditionType.RESTARTING)
            or cond.has_condition(s, ConditionType.MIGRATING)
            or (st.was_running and not cond.is_running(s))
        )
        if st.last_tick is not None and down:
            st.buckets[BUCKET_RESTART] += now - st.last_tick
        st.last_tick = now
        if cond.is_running(s):
            st.was_running = True

    def _ingest_workers(self, st: _JobState, pods) -> int:
        """Reset-aware per-worker deltas; the coordinator's land in the
        job buckets. Returns the coordinator's step delta this tick."""
        coord_dsteps = 0
        for p in pods:
            ts = p.status.train_stats
            if not ts:
                continue
            name = p.metadata.name
            w = st.workers.get(name)
            if w is None or w.uid != p.metadata.uid:
                # new incarnation: fresh base — its counters restarted
                # from zero, so deltas resume continuously (never negative)
                w = st.workers[name] = _Worker(uid=p.metadata.uid)
                if st.seed_bases:
                    # adoption tick: this worker's cumulative is already
                    # inside the telemetry blob we resumed from — base at
                    # its CURRENT counters (zero delta), never recharge it
                    w.steps = int(ts.get("steps", 0) or 0)
                    w.buckets = {
                        k: float((ts.get("buckets") or {}).get(k, 0.0)
                                 or 0.0)
                        for k in TRAIN_BUCKETS
                    }
            new_steps = int(ts.get("steps", 0) or 0)
            new_buckets = dict(ts.get("buckets") or {})
            dsteps = new_steps - w.steps
            if dsteps < 0:  # in-place reset (defensive): value IS the delta
                w.buckets = {}
                dsteps = new_steps
            dbuckets = {}
            for k in TRAIN_BUCKETS:
                nv = float(new_buckets.get(k, 0.0) or 0.0)
                ov = float(w.buckets.get(k, 0.0))
                dbuckets[k] = nv if nv < ov else nv - ov
            w.steps = new_steps
            w.step = int(ts.get("step", 0) or 0)
            w.p50_ms = float(ts.get("step_p50_ms", 0.0) or 0.0)
            w.buckets = {k: float(new_buckets.get(k, 0.0) or 0.0)
                         for k in TRAIN_BUCKETS}
            if p.metadata.labels.get(LABEL_REPLICA_INDEX) == "0":
                for k, v in dbuckets.items():
                    if v > 0:
                        st.buckets[k] += v
                st.productive_s += max(0.0, dbuckets.get("compute", 0.0))
                st.steps_total = max(st.steps_total, w.step)
                coord_dsteps = max(0, dsteps)
                if coord_dsteps > 0:
                    total = 0.0
                    for k, v in dbuckets.items():
                        if v > 0:
                            metrics.step_latency.observe(
                                v / coord_dsteps, bucket=k)
                            total += v
                    metrics.step_latency.observe(
                        total / coord_dsteps, bucket="step")
        return coord_dsteps

    def _close_restart_span(self, st: _JobState, now: float) -> None:
        if st.restart_at is None:
            return
        coord = st.workers.get(st.coord_name)
        if (coord is not None and coord.steps > 0
                and coord.uid != st.restart_coord_uid):
            # the RELAUNCHED coordinator completed a step: the outage span
            # closes (evict → relaunch → first completed step)
            metrics.restart_to_first_step.observe(
                max(0.0, now - st.restart_at),
                kind=st.restart_kind or "restart",
            )
            st.restart_at = None

    def _detect_skew(self, job: TPUJob, st: _JobState, pods) -> None:
        reporting = []
        for p in pods:
            w = st.workers.get(p.metadata.name)
            if (w is not None and w.uid == p.metadata.uid
                    and w.steps >= SKEW_MIN_STEPS and w.p50_ms > 0):
                reporting.append((w.p50_ms, p))
        cleared = True
        if len(reporting) >= 2:
            med = statistics.median(p50 for p50, _ in reporting)
            worst_p50, worst = max(reporting, key=lambda r: r[0])
            if (med > 0 and worst_p50 > self.skew_factor * med
                    and worst_p50 - med > self.skew_min_ms):
                cleared = False
                node = worst.spec.node_name or "?"
                who = f"{worst.metadata.namespace}/{worst.metadata.name}"
                st.straggler = f"{who}@{node}"
                metrics.job_stragglers.set(1, job=st.key)
                msg = (f"worker pod {worst.metadata.name} on node "
                       f"{node} is a straggler: step p50 "
                       f"{worst_p50:.1f}ms vs gang median {med:.1f}ms "
                       f"(>{self.skew_factor:g}x)")
                if st.straggler_uid != worst.metadata.uid:
                    # the Event fires once per straggler incarnation...
                    st.straggler_uid = worst.metadata.uid
                    self.recorder.event(job, WARNING, "Straggler", msg)
                # ...but the CONDITION is level-triggered every tick: a
                # flip whose rv-pinned patch lost a write race (or was
                # erased by the controller's own conditions write) is
                # re-stamped next tick — the fresh-read no-op elision in
                # update_job_conditions makes the steady state free
                self._set_straggler_condition(job, True,
                                              cond.REASON_STRAGGLER, msg)
        # clear on the JOB's durable condition too, not just in-memory
        # state: a leader failover hands the new aggregator a fresh
        # _JobState, and a healed gang's still-active Straggler condition
        # must flip off even though THIS aggregator never set it
        if cleared and (st.straggler or cond.has_condition(
                job.status, ConditionType.STRAGGLER)):
            st.straggler = ""
            st.straggler_uid = ""
            metrics.job_stragglers.set(0, job=st.key)
            self._set_straggler_condition(
                job, False, cond.REASON_STRAGGLER_CLEARED,
                "step-time skew back under the threshold",
            )

    def _set_straggler_condition(self, job: TPUJob, active: bool,
                                 reason: str, message: str) -> None:
        """Flip the auxiliary Straggler condition. A merge patch replaces
        the WHOLE conditions array, and the reconcile loop writes the
        same array from its own reads — so this is a fresh-read RMW with
        an rv precondition (the sanctioned patch-with-rv shape): a
        controller write landing in between bounces this patch as a
        Conflict instead of this patch resurrecting a stale array (e.g.
        erasing a just-written Failed condition). Next tick retries."""
        try:
            cur = self.store.get("TPUJob", job.namespace, job.name)
        except NotFound:
            return
        if cur.metadata.uid != job.metadata.uid:
            return  # recreated same-name job: not ours to stamp
        if not cond.update_job_conditions(
            cur.status, ConditionType.STRAGGLER, reason, message, active
        ):
            return
        try:
            self.store.patch(
                "TPUJob", job.namespace, job.name,
                {"metadata": {
                    "uid": cur.metadata.uid,
                    "resource_version": cur.metadata.resource_version,
                 },
                 "status": {"conditions": [
                     c.to_dict() for c in cur.status.conditions
                 ]}},
                subresource="status",
            )
        except (Conflict, NotFound):
            pass  # lost the write race / deleted; next tick re-evaluates

    def _write_rollup(self, job: TPUJob, st: _JobState, now: float,
                      coord_dsteps: int) -> None:
        wall = max(1e-9, now - (st.admitted_at or now) - st.excluded_s)
        goodput = max(0.0, min(1.0, st.productive_s / wall))
        if st.steps_total <= 0:
            return  # nothing reported yet: no gauge, no telemetry
        # export only once steps exist — a brand-new job mid-compile must
        # not page goodput-collapse before it ever could have stepped
        metrics.job_goodput_ratio.set(round(goodput, 4), job=st.key)
        coord = st.workers.get(st.coord_name)
        stalls = {k: v for k, v in st.buckets.items() if k != "compute"}
        dominant = max(stalls, key=stalls.get) if any(
            v > 0 for v in stalls.values()) else ""
        blob = {
            "goodput": round(goodput, 4),
            "step_p50_ms": round(coord.p50_ms, 3) if coord else 0.0,
            "steps": st.steps_total,
            "dominant_stall": dominant,
            "buckets": {k: round(v, 3) for k, v in st.buckets.items()},
            "straggler": st.straggler,
            "workers_reporting": sum(
                1 for w in st.workers.values() if w.steps > 0
            ),
        }
        # No-op elision must ignore the goodput ratio itself: it is derived
        # from WALL time, so it drifts every tick even when no worker has
        # reported anything new. Comparing it would turn every idle tick
        # into a status write — the exact never-quiesces defect convcheck
        # exists to catch. The gauge above still tracks the live ratio;
        # the persisted rollup only moves when telemetry-derived fields do.
        def _stable(b):
            return {k: v for k, v in (b or {}).items() if k != "goodput"}
        if _stable(blob) == _stable(st.telemetry):
            return  # no-op elision: an idle rollup costs zero writes
        try:
            self.store.patch(
                "TPUJob", job.namespace, job.name,
                {"metadata": {"uid": job.metadata.uid},
                 "status": {"train_telemetry": blob}},
                subresource="status",
            )
            st.telemetry = blob
        except (Conflict, NotFound):
            pass  # recreated/deleted under us; next tick re-evaluates
