"""ICI-topology-aware gang placement.

The reference delegates gang scheduling to Volcano PodGroups
(minMember = workers+1, v2/pkg/controller/mpi_job_controller.go:573,1215-1237)
and knows nothing about interconnect topology — MPI ranks are
placement-agnostic. On TPU, placement IS the performance model: the hosts of a
job must form a contiguous slice so collectives ride ICI, and each host's
position in the slice determines its coordinates in the device mesh
(SURVEY.md §2.5, §7 "hard parts": topology-aware gang scheduling).

This module computes the slice-host layout for a job:

- A slice topology like ``4x4x4`` (chips) is split into per-host blocks using
  the family's chips-per-host geometry (v4/v5p hosts own a ``2x2x1`` block of
  the chip mesh; v5e/v6e hosts own ``2x2`` of a 2-D mesh; the ``cpu`` test
  family is 1 chip per host, 1-D).
- Every worker index is assigned (a) a host coordinate in the host mesh and
  (b) the base coordinate of its chip block — stamped into pod annotations so
  the runtime can build a ``jax.sharding.Mesh`` whose axes line up with
  physical ICI neighbours (runtime/topology.py consumes these).

Placement is atomic: either every worker fits the declared topology or the
job cannot be placed (gang semantics; a TPU slice is inherently all-or-nothing).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from mpi_operator_tpu.api.types import (
    HOST_BLOCK,
    SliceSpec,
    compute_host_mesh,
    host_block_for,
)

ANNOTATION_HOST_COORD = "tpujob.dev/host-coord"
ANNOTATION_CHIP_BASE = "tpujob.dev/chip-base"
ANNOTATION_HOST_MESH = "tpujob.dev/host-mesh"
ANNOTATION_TOPOLOGY = "tpujob.dev/topology"
ANNOTATION_SLICE_ID = "tpujob.dev/slice-id"
ANNOTATION_NUM_SLICES = "tpujob.dev/num-slices"


class PlacementError(ValueError):
    pass


@dataclass
class SlicePlacement:
    """The computed layout for one job's gang."""

    topology: Tuple[int, ...]  # per-slice chip mesh shape
    host_block: Tuple[int, ...]  # chips-per-host block shape
    host_mesh: Tuple[int, ...]  # per-slice host mesh (topology / host_block)
    host_coords: List[Tuple[int, ...]] = field(default_factory=list)  # per worker index
    chip_bases: List[Tuple[int, ...]] = field(default_factory=list)
    num_slices: int = 1
    slice_ids: List[int] = field(default_factory=list)  # per worker index

    @property
    def num_hosts(self) -> int:
        return len(self.host_coords)

    @property
    def hosts_per_slice(self) -> int:
        return len(self.host_coords) // max(self.num_slices, 1)

    def annotations_for(self, index: int) -> Dict[str, str]:
        return {
            ANNOTATION_HOST_COORD: "x".join(map(str, self.host_coords[index])),
            ANNOTATION_CHIP_BASE: "x".join(map(str, self.chip_bases[index])),
            ANNOTATION_HOST_MESH: "x".join(map(str, self.host_mesh)),
            ANNOTATION_TOPOLOGY: "x".join(map(str, self.topology)),
            ANNOTATION_SLICE_ID: str(self.slice_ids[index]),
            ANNOTATION_NUM_SLICES: str(self.num_slices),
        }


def _default_topology(block: Tuple[int, ...], num_workers: int) -> Tuple[int, ...]:
    """Derive a chip topology when the job didn't declare one: a 1-D layout of
    num_workers host blocks along the first axis."""
    dims = list(block)
    dims[0] *= num_workers
    return tuple(dims)


def place_workers(slice_spec: SliceSpec, num_workers: int) -> SlicePlacement:
    """Compute the gang layout. Raises PlacementError when the topology cannot
    host exactly ``num_workers`` hosts (atomic/gang: no partial placement).
    Uses the same host_block_for/compute_host_mesh helpers as admission
    validation, so a validated spec is always placeable.

    Multi-slice (``num_slices > 1``): workers divide evenly into
    ``num_slices`` identical ICI slices; worker i sits in slice
    ``i // hosts_per_slice`` at within-slice coordinate
    ``i % hosts_per_slice``. Slice identity is stamped on each pod so the
    runtime can build the hybrid ICI×DCN mesh (runtime/topology.py)."""
    family = slice_spec.accelerator
    if family not in HOST_BLOCK:
        raise PlacementError(f"unknown accelerator family {family!r}")
    block = host_block_for(family, slice_spec.chips_per_host)
    if block is None:
        raise PlacementError(
            f"{slice_spec.chips_per_host} chips per host is not a legal "
            f"{family} host configuration"
        )

    num_slices = max(slice_spec.num_slices, 1)
    if num_workers % num_slices != 0:
        raise PlacementError(
            f"{num_workers} workers do not divide evenly across "
            f"{num_slices} slices — gang placement is all-or-nothing"
        )
    per_slice = num_workers // num_slices

    if slice_spec.topology:
        topo = tuple(int(p) for p in slice_spec.topology.split("x"))
    else:
        topo = _default_topology(block, per_slice)
    host_mesh_t = compute_host_mesh(topo, block)
    if host_mesh_t is None:
        raise PlacementError(
            f"topology {topo} is not divisible into {family} host blocks of {block}"
        )
    host_mesh = list(host_mesh_t)
    total_hosts = 1
    for h in host_mesh:
        total_hosts *= h
    if total_hosts != per_slice:
        raise PlacementError(
            f"topology {'x'.join(map(str, topo))} holds {total_hosts} "
            f"{family} hosts but the job has {per_slice} workers per slice "
            f"— gang placement is all-or-nothing"
        )

    # Row-major host enumeration: worker index i ↔ host coordinate. Row-major
    # matches jax mesh_utils' device ordering so mesh axes line up with ICI.
    placement = SlicePlacement(
        topology=topo,
        host_block=block,
        host_mesh=tuple(host_mesh),
        num_slices=num_slices,
    )
    for i in range(num_workers):
        within = i % per_slice
        coord = []
        rem = within
        for dim in reversed(host_mesh):
            coord.append(rem % dim)
            rem //= dim
        coord = tuple(reversed(coord))
        placement.host_coords.append(coord)
        placement.chip_bases.append(tuple(c * b for c, b in zip(coord, block)))
        placement.slice_ids.append(i // per_slice)
    return placement
