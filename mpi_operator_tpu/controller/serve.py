"""TPUServe controller: the serving workload class's reconcile loop.

Batch (TPUJob) runs to completion; serving runs until told otherwise. This
controller manages N long-lived inference GANGS ("replicas", each a
``workers_per_replica``-host gang with its own PodGroup, admitted by the
SAME gang scheduler that admits batch — at serving priority), with:

- **Readiness gates**: a replica serves only when every member pod is
  Running AND ready (``pod.status.ready`` — the executor flips it after
  model load/warmup, the kubelet-readiness-probe equivalent). Replica
  readiness drives Available/Progressing conditions and the rollout below.
- **Rolling generation-based updates** — the serving generalization of
  TPUJob's ``restart_generation``: a hash of the pod-affecting spec
  (template + slice + gang size) names a GENERATION; when it changes the
  controller surges a new-generation replica (up to ``max_surge`` above
  desired), waits for it to pass the readiness gate, and only then drains
  an old-generation replica — ready count never dips below
  ``desired - max_unavailable`` (0 by default: zero unready windows, the
  serve bench's tripwire). Pods carry the generation in the SAME
  ``tpujob.dev/generation`` label batch gangs use, so the trail
  invariants (one generation per gang, monotone) hold unchanged.
- **Self-healing**: a replica with a terminal pod (node loss eviction,
  crash, preemption) is torn down whole — gang coherence, same argument
  as the batch controller's gang-scoped restarts — and a fresh replica
  (new id, current generation) replaces it.
- **Replica ids are monotonic and never reused**, so `ctl trace` and the
  invariant checkers can tell every gang apart by name alone.

Scale decisions live elsewhere: the autoscaler (controller/autoscaler.py)
writes ``spec.replicas``; this loop only makes the world match it.
"""

from __future__ import annotations

import hashlib
import json
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from mpi_operator_tpu.api import conditions as cond
from mpi_operator_tpu.api.defaults import (
    effective_disruption_budget,
    set_serve_defaults,
)
from mpi_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    OwnerReference,
    ServeConditionType,
    TPUServe,
)
from mpi_operator_tpu.api.validation import validate_tpuserve
from mpi_operator_tpu.controller.controller import (
    ENV_ACCELERATOR,
    ENV_CHIPS_PER_HOST,
    ENV_COORDINATOR,
    ENV_HOST_COORD,
    ENV_HOST_ID,
    ENV_HOST_MESH,
    ENV_NAMESPACE,
    ENV_NUM_HOSTS,
    ENV_TOPOLOGY,
    LABEL_GENERATION,
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_ROLE,
)
from mpi_operator_tpu.controller.placement import PlacementError, place_workers
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.cache import InformerCache
from mpi_operator_tpu.machinery.events import NORMAL, WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    ANNOTATION_MAINTENANCE_AT,
    NODE_NAMESPACE,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodPhase,
    PodSpec,
)
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
    WatchEvent,
    diff_merge_patch,
)
from mpi_operator_tpu.machinery.workqueue import RateLimitingQueue
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.serve")

# serving-pod labels (the batch labels plus the serve identity pair)
LABEL_SERVE_NAME = "tpujob.dev/serve-name"
LABEL_SERVE_REPLICA = "tpujob.dev/serve-replica"
ROLE_SERVE = "serve"

# rendezvous env additions for serving gangs (batch's TPUJOB_* contract
# carries the gang geometry; these carry the serving identity)
ENV_SERVE_NAME = "TPUSERVE_NAME"
ENV_SERVE_REPLICA = "TPUSERVE_REPLICA"
ENV_SERVE_GENERATION = "TPUSERVE_GENERATION"

# per-replica rendezvous port: serving gangs are placed by replica id, so a
# deterministic hash slot suffices (two replicas of one serve never share a
# coordinator; cross-serve collisions are as harmless as batch's hash probe
# misses — the executor binds per-process)
SERVE_PORT_BASE = 8600
SERVE_PORT_RANGE = 1024

EVENT_VALIDATION_ERROR = "ValidationError"
EVENT_PLACEMENT_ERROR = "PlacementError"
EVENT_ROLLOUT = "RolloutStarted"
EVENT_REPLICA_FAILED = "ReplicaFailed"
EVENT_SCALED_TO_ZERO = "ScaledToZero"


def compute_template_hash(serve: TPUServe) -> str:
    """The generation fingerprint: everything that lands in a pod. Computed
    over the DEFAULTED spec so an explicit default and an omitted field
    hash identically (no phantom rollouts)."""
    payload = json.dumps(
        {
            "template": serve.spec.template.to_dict(),
            "slice": serve.spec.slice.to_dict(),
            "workers": serve.spec.workers_per_replica,
        },
        sort_keys=True,
    )
    return hashlib.sha1(payload.encode()).hexdigest()[:12]


def serve_port(replica_id: int) -> int:
    return SERVE_PORT_BASE + replica_id % SERVE_PORT_RANGE


def group_replicas(pods: List[Pod]) -> Dict[int, List[Pod]]:
    """Pods → replica-id → member pods (label-driven, level-triggered:
    observed state is the only input)."""
    out: Dict[int, List[Pod]] = {}
    for p in pods:
        rid = p.metadata.labels.get(LABEL_SERVE_REPLICA)
        if rid is None:
            continue
        try:
            out.setdefault(int(rid), []).append(p)
        except ValueError:
            continue
    for members in out.values():
        members.sort(
            key=lambda p: int(p.metadata.labels.get(LABEL_REPLICA_INDEX, "0"))
        )
    return out


def replica_ready(members: List[Pod], workers: int) -> bool:
    """The readiness gate: full gang, every pod Running AND ready."""
    return len(members) >= workers and all(
        p.status.phase == PodPhase.RUNNING and p.status.ready
        for p in members
    )


def replica_generation(members: List[Pod]) -> int:
    """The generation a replica's pods were stamped with (uniform by
    construction; the min is the safe read if a heal ever mixed them)."""
    gens = []
    for p in members:
        try:
            gens.append(int(p.metadata.labels.get(LABEL_GENERATION, "0")))
        except ValueError:
            pass
    return min(gens) if gens else 0


@dataclass
class ServeControllerOptions:
    namespace: Optional[str] = None
    threadiness: int = 1


class TPUServeController:
    """Level-triggered reconciler for TPUServe over an ObjectStore —
    deliberately the same shape as TPUJobController (watch/informer pump →
    rate-limited workqueue → sync_handler) so the operational story
    (leader-only, informer reads, uid-pinned status patches) is uniform
    across both workload classes."""

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        options: Optional[ServeControllerOptions] = None,
        cache: Optional["InformerCache"] = None,
    ):
        self.store = store
        self.cache = cache
        self.read = cache if cache is not None else store
        self.options = options or ServeControllerOptions()
        self.recorder = recorder or EventRecorder(
            store, component="tpuserve-controller"
        )
        self.queue = RateLimitingQueue()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._watch_q = None
        self._write_status = self._default_write_status
        self._lock = threading.Lock()
        # serve key → causal parent of the next reconcile (watch origin)
        self._trace_links: Dict[str, object] = {}
        # serve uid → trace id stamped by this controller (informer-lag memo)
        self._stamped_traces: Dict[str, str] = {}
        # (serve uid, replica id) already announced ready — the
        # serve.replica_ready span and its readiness-latency observation
        # fire once per gang
        self._ready_noted: set = set()
        # serve uid → last effective desired (stamps last_scale_*_time)
        self._last_desired: Dict[str, int] = {}
        # node → last maintenance-at value observed through the pump: a
        # CHANGE (notice stamped / rescheduled / cleared) re-enqueues every
        # serve so drain-aware migration starts without waiting for a pod
        # event — heartbeat-only Node updates stay cheap (no enqueue)
        self._node_maint_seen: Dict[str, Optional[str]] = {}

    # ------------------------------------------------------------------
    # run loop
    # ------------------------------------------------------------------

    def run(self) -> None:
        if self.cache is not None:
            self.cache.add_event_handler(lambda etype, obj: self._pump_obj(obj))
        else:
            self._watch_q = self.store.watch(None)
            pump = threading.Thread(
                target=self._pump, name="tpuserve-watch-pump", daemon=True
            )
            pump.start()
            self._threads.append(pump)
        for i in range(self.options.threadiness):
            t = threading.Thread(
                target=self._run_worker, name=f"tpuserve-worker-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        prime = threading.Thread(
            target=self._prime, name="tpuserve-prime", daemon=True
        )
        prime.start()
        self._threads.append(prime)

    def _wait_cache_synced(self) -> bool:
        if self.cache is None:
            return True
        while not self._stop.is_set():
            if self.cache.wait_for_sync(0.2):
                return True
        return False

    def _prime(self) -> None:
        if not self._wait_cache_synced():
            return
        for serve in self.read.list("TPUServe", self.options.namespace):
            self.enqueue(serve.metadata.key())

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        if self._watch_q is not None:
            self.store.stop_watch(self._watch_q)
        for t in self._threads:
            t.join(timeout=5)

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                ev: WatchEvent = self._watch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if ev.kind == "Event":
                continue
            trace.set_delivery(getattr(ev, "trace", None))
            try:
                self._pump_obj(ev.obj)
            finally:
                trace.clear_delivery()

    def _pump_obj(self, obj) -> None:
        ns = obj.metadata.namespace
        if obj.kind == "Node":
            self._pump_node(obj)
            return
        if self.options.namespace is not None and ns != self.options.namespace:
            return
        if obj.kind == "TPUServe":
            self._note_trigger(obj.metadata.key())
            self.enqueue(obj.metadata.key())
            return
        owner = self._controller_owner(obj)
        if owner is not None:
            self._note_trigger(f"{ns}/{owner.name}")
            self.enqueue(f"{ns}/{owner.name}")

    def _pump_node(self, node) -> None:
        """Maintenance-notice wakeups: a node whose ``maintenance-at``
        annotation appears, changes, or clears re-enqueues every serve in
        scope (serves are few; per-heartbeat Node events cost one dict
        probe). Without this a drain would wait for the next unrelated
        pod event before surge-first migration began."""
        name = node.metadata.name
        val = node.metadata.annotations.get(ANNOTATION_MAINTENANCE_AT)
        with self._lock:
            seen = self._node_maint_seen.get(name)
            if seen == val:
                return
            self._node_maint_seen[name] = val
            if len(self._node_maint_seen) > 65536:
                self._node_maint_seen.clear()  # bounded; re-wake is benign
        for serve in self.read.list("TPUServe", self.options.namespace):
            self.enqueue(serve.metadata.key())

    def _note_trigger(self, key: str) -> None:
        link = trace.get_delivery()
        if link is not None:
            with self._lock:
                self._trace_links[key] = link

    @staticmethod
    def _controller_owner(obj) -> Optional[OwnerReference]:
        for ref in obj.metadata.owner_references:
            if ref.controller and ref.kind == "TPUServe":
                return ref
        return None

    def _run_worker(self) -> None:
        if not self._wait_cache_synced():
            return
        while True:
            key = self.queue.get(timeout=0.2)
            if key is None:
                if self._stop.is_set() or self.queue.shutting_down:
                    return
                continue
            try:
                ok = self.sync_handler(key)
            except Exception:
                log.exception("serve sync %s failed", key)
                ok = False
            if ok:
                self.queue.forget(key)
            else:
                self.queue.add_rate_limited(key)
            self.queue.done(key)

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------

    def sync_handler(self, key: str) -> bool:
        with self._lock:
            link = self._trace_links.pop(key, None)
        t0 = time.perf_counter()
        try:
            with trace.start_span(
                "serve.reconcile", parent=link, attrs={"serve": key}
            ):
                return self._sync(key)
        except (Conflict, AlreadyExists):
            return False  # stale cached read: requeue past the watch echo
        except RuntimeError as e:
            log.warning("serve sync %s: %s", key, e)
            return False
        finally:
            metrics.serve_reconcile_latency.observe(time.perf_counter() - t0)

    def _sync(self, key: str) -> bool:
        namespace, name = key.split("/", 1)
        serve = self.read.try_get("TPUServe", namespace, name)
        if serve is None:
            self._reap_orphans(namespace, name)
            # a deleted serve's per-object gauges must stop exporting
            # their last values (and churn must not grow the registry)
            metrics.serve_replicas_ready.remove(serve=key)
            metrics.serve_desired_replicas.remove(serve=key)
            return True
        set_serve_defaults(serve)
        errs = validate_tpuserve(serve)
        if errs:
            self.recorder.event(
                serve, WARNING, EVENT_VALIDATION_ERROR, "; ".join(errs)
            )
            return True
        self._ensure_trace_id(serve)

        # --- generation: the rolling-update trigger -------------------
        h = compute_template_hash(serve)
        if serve.status.template_hash and serve.status.template_hash != h:
            old_gen = serve.status.serve_generation
            serve.status.serve_generation += 1
            self.recorder.event(
                serve, NORMAL, EVENT_ROLLOUT,
                f"template changed: rolling generation {old_gen} → "
                f"{serve.status.serve_generation}",
            )
            # the rollout anchor span `ctl trace <serve>` renders: the
            # per-replica launch/ready/drain spans that execute the
            # rollout all follow it in the serve's trace
            with trace.start_span(
                "serve.rollout",
                trace_id=self._trace_id(serve),
                attrs={
                    "serve": key,
                    "from_generation": old_gen,
                    "to_generation": serve.status.serve_generation,
                },
            ):
                pass
        serve.status.template_hash = h
        gen = serve.status.serve_generation

        desired = serve.spec.replicas or 0
        workers = serve.spec.workers_per_replica
        try:
            placement = place_workers(serve.spec.slice, workers)
        except PlacementError as e:
            self.recorder.event(serve, WARNING, EVENT_PLACEMENT_ERROR, str(e))
            return True

        pods = self.read.list(
            "Pod", namespace, selector={LABEL_SERVE_NAME: name}
        )
        replicas = group_replicas(pods)

        # --- tear down failed gangs (gang coherence, as in batch) ------
        live: Dict[int, List[Pod]] = {}
        for rid, members in sorted(replicas.items()):
            if any(p.is_finished() for p in members):
                first = next(p for p in members if p.is_finished())
                self.recorder.event(
                    serve, WARNING, EVENT_REPLICA_FAILED,
                    f"replica {rid}: pod {first.metadata.name} "
                    f"{first.status.phase} "
                    f"({first.status.reason or 'Error'}); tearing the gang "
                    f"down for replacement",
                )
                self._drain_replica(serve, rid, members, reason="failed")
                continue
            live[rid] = members

        ready_ids = {
            rid for rid, members in live.items()
            if replica_ready(members, workers)
        }
        self._note_ready(serve, live, ready_ids, gen)
        new_gen = {
            rid for rid, members in live.items()
            if replica_generation(members) == gen
        }

        # --- drain-awareness (the disruption plane, ISSUE 14) ----------
        # replicas with a member on a maintenance-noticed node are DOOMED:
        # they migrate surge-first — a replacement gang is created (and
        # placed elsewhere; the scheduler excludes cordoned nodes and
        # penalizes imminent-maintenance ones), waits for readiness, and
        # only then is the doomed replica retired, never letting
        # ready_total dip below the serve's DisruptionBudget
        draining_nodes = self._draining_nodes()
        doomed = {
            rid for rid, members in live.items()
            if any(p.spec.node_name in draining_nodes for p in members)
        }

        # --- heal partial gangs (crash mid-create) --------------------
        for rid, members in live.items():
            if len(members) < workers:
                have = {
                    int(p.metadata.labels.get(LABEL_REPLICA_INDEX, "0"))
                    for p in members
                }
                rgen = replica_generation(members)
                for j in range(workers):
                    if j not in have:
                        self._create_pod(serve, rid, j, rgen, placement)

        # --- surge new-generation gangs up to desired ------------------
        # doomed replicas don't count toward coverage: a gang on a
        # draining node needs a replacement REGARDLESS of its generation
        # (the surge-first half of checkpoint-free serve migration)
        need = desired - len(new_gen - doomed)
        budget = desired + serve.spec.max_surge - len(live)
        for _ in range(max(0, min(need, budget))):
            rid = serve.status.next_replica_id
            serve.status.next_replica_id += 1
            self._launch_replica(serve, rid, gen, workers, placement)
            live[rid] = []  # counts against desired/surge this pass
            new_gen.add(rid)

        # --- drain: doomed replicas, old generations, scale-down -------
        # One rule serves rollout, scale-down AND maintenance migration:
        # while more gangs are live than needed, retire the best victim
        # whose removal keeps ready_total above the floor. Doomed gangs go
        # first (their node is dying), then old generations, then the
        # newest new-generation ids. A ready victim is only retired when
        # the floor survives it — rollouts floor at
        # desired - max_unavailable (the zero-unready-window guarantee),
        # doomed victims additionally at the DisruptionBudget.
        floor = desired - serve.spec.max_unavailable
        # ONE budget rule, shared with the DrainController's blocked-drain
        # reporting (api/defaults.py) so gate and gauge can never disagree
        dbudget = effective_disruption_budget(serve)
        ready_total = len(ready_ids)
        # a surplus exists while gangs exceed desired, OR while a doomed
        # gang still has a ready surged replacement able to stand in
        while len(live) > desired or (doomed & set(live)):
            victim = self._pick_victim(live, new_gen, ready_ids,
                                       doomed=doomed)
            if victim is None:
                break
            if victim not in doomed and len(live) <= desired:
                break  # only doomed gangs may retire below the surplus
            vfloor = max(floor, dbudget) if victim in doomed else floor
            if victim in ready_ids and ready_total - 1 < vfloor:
                break  # retiring now would violate the budget/floor
            if (victim in doomed and victim not in ready_ids
                    and len(live) <= desired and ready_total < vfloor):
                # an unready doomed gang with no ready replacement yet:
                # keep it (it may still be serving warmup traffic) until
                # the surge covers the floor — the DrainController
                # reports this state as drain_budget_blocked
                break
            members = live.pop(victim)
            if victim in ready_ids:
                ready_ids.discard(victim)
                ready_total -= 1
            new_gen.discard(victim)
            if victim in doomed:
                doomed.discard(victim)
                with trace.start_span(
                    "drain.migrate_replica",
                    trace_id=self._trace_id(serve),
                    attrs={
                        "serve": key, "replica": victim,
                        "nodes": sorted({
                            p.spec.node_name for p in members
                            if p.spec.node_name in draining_nodes
                        }),
                        "ready_total_after": ready_total,
                        "budget": dbudget,
                    },
                ):
                    self._drain_replica(serve, victim, members,
                                        reason="maintenance")
                continue
            self._drain_replica(
                serve, victim, members,
                reason=("rollout" if members
                        and replica_generation(members) != gen
                        else "scale-down"),
            )

        # --- status mirror --------------------------------------------
        self._update_status(serve, live, ready_ids, new_gen, desired)
        return self._write_status(serve)

    # ------------------------------------------------------------------
    # dependents
    # ------------------------------------------------------------------

    def _trace_id(self, serve: TPUServe) -> Optional[str]:
        return serve.metadata.annotations.get(trace.ANNOTATION_TRACE_ID)

    def _ensure_trace_id(self, serve: TPUServe) -> None:
        tid = self._trace_id(serve)
        if not tid:
            with self._lock:
                tid = self._stamped_traces.get(serve.metadata.uid)
        if not tid:
            tid = trace.new_trace_id()
            try:
                self.store.patch(
                    "TPUServe", serve.namespace, serve.name,
                    {"metadata": {
                        "uid": serve.metadata.uid,
                        "annotations": {trace.ANNOTATION_TRACE_ID: tid},
                    }},
                )
            except (NotFound, Conflict):
                return
            with self._lock:
                self._stamped_traces[serve.metadata.uid] = tid
                while len(self._stamped_traces) > 4096:
                    self._stamped_traces.pop(next(iter(self._stamped_traces)))
        serve.metadata.annotations[trace.ANNOTATION_TRACE_ID] = tid
        sp = trace.TRACER.current_span()
        if sp is not None:
            sp.adopt_trace(tid)

    def _owner_ref(self, serve: TPUServe) -> OwnerReference:
        return OwnerReference(
            kind="TPUServe", name=serve.name, uid=serve.metadata.uid,
            controller=True,
        )

    def _draining_nodes(self) -> set:
        """Nodes with a maintenance notice: replicas bound there are doomed
        and migrate surge-first. Informer-cached — one list per reconcile,
        zero store traffic."""
        return {
            n.metadata.name
            for n in self.read.list("Node", NODE_NAMESPACE)
            if ANNOTATION_MAINTENANCE_AT in n.metadata.annotations
        }

    def _reap_orphans(self, namespace: str, name: str) -> None:
        """Cascade delete for a deleted serve (kube GC semantics), guarded
        by the controller owner ref exactly like the batch reaper."""
        for kind in ("Pod", "PodGroup"):
            for obj in self.read.list(
                kind, namespace, selector={LABEL_SERVE_NAME: name}
            ):
                owner = self._controller_owner(obj)
                if owner is None or owner.name != name:
                    continue
                self.store.try_delete(kind, namespace, obj.metadata.name)

    def _launch_replica(self, serve: TPUServe, rid: int, gen: int,
                        workers: int, placement) -> None:
        """One new serving gang: PodGroup (the gang-scheduler admission
        unit, at serving priority) + every member pod, under a
        serve.replica_launch span in the serve's trace."""
        with trace.start_span(
            "serve.replica_launch",
            trace_id=self._trace_id(serve),
            attrs={
                "serve": serve.metadata.key(), "replica": rid,
                "generation": gen, "workers": workers,
            },
        ):
            gang = serve.gang_name(rid)
            pg = PodGroup(
                metadata=ObjectMeta(
                    name=gang,
                    namespace=serve.namespace,
                    labels={
                        LABEL_JOB_NAME: gang,
                        LABEL_SERVE_NAME: serve.name,
                        LABEL_SERVE_REPLICA: str(rid),
                    },
                    owner_references=[self._owner_ref(serve)],
                ),
                spec=PodGroupSpec(
                    min_member=workers,
                    priority_class=serve.spec.priority_class,
                ),
            )
            try:
                self.store.create(pg)
            except AlreadyExists:
                pass  # level-triggered retry after a half-done pass
            for j in range(workers):
                self._create_pod(serve, rid, j, gen, placement)

    def _create_pod(self, serve: TPUServe, rid: int, index: int, gen: int,
                    placement) -> None:
        tmpl = serve.spec.template
        container = Container.from_dict(tmpl.container.to_dict())
        env = dict(container.env)
        gang = serve.gang_name(rid)
        env.update({
            ENV_SERVE_NAME: serve.name,
            ENV_SERVE_REPLICA: str(rid),
            ENV_SERVE_GENERATION: str(gen),
            ENV_NAMESPACE: serve.namespace,
            ENV_COORDINATOR: f"{serve.pod_name(rid, 0)}:{serve_port(rid)}",
            ENV_NUM_HOSTS: str(serve.spec.workers_per_replica),
            ENV_HOST_ID: str(index),
            ENV_CHIPS_PER_HOST: str(serve.spec.slice.chips_per_host),
            ENV_ACCELERATOR: serve.spec.slice.accelerator,
            ENV_TOPOLOGY: "x".join(map(str, placement.topology)),
            ENV_HOST_MESH: "x".join(map(str, placement.host_mesh)),
            ENV_HOST_COORD: "x".join(map(str, placement.host_coords[index])),
        })
        container.env = env
        labels = dict(tmpl.labels)
        labels.update({
            LABEL_JOB_NAME: gang,  # the gang scheduler's grouping key
            LABEL_SERVE_NAME: serve.name,
            LABEL_SERVE_REPLICA: str(rid),
            LABEL_ROLE: ROLE_SERVE,
            LABEL_REPLICA_INDEX: str(index),
            LABEL_GENERATION: str(gen),
        })
        annotations = dict(tmpl.annotations)
        annotations.update(placement.annotations_for(index))
        tid = self._trace_id(serve)
        if tid:
            annotations[trace.ANNOTATION_TRACE_ID] = tid
        pod = Pod(
            metadata=ObjectMeta(
                name=serve.pod_name(rid, index),
                namespace=serve.namespace,
                labels=labels,
                annotations=annotations,
                owner_references=[self._owner_ref(serve)],
            ),
            spec=PodSpec(
                container=container,
                hostname=serve.pod_name(rid, index),
                restart_policy="Never",  # the controller owns replacement
                node_selector=dict(tmpl.node_selector),
                scheduler_name=tmpl.scheduler_name,
                priority_class=tmpl.priority_class
                or serve.spec.priority_class,
            ),
        )
        try:
            self.store.create(pod)
        except AlreadyExists:
            pass  # informer lag on our own create; the echo reconciles

    def _drain_replica(self, serve: TPUServe, rid: int, members: List[Pod],
                       *, reason: str) -> None:
        """Retire one gang whole: delete its pods + PodGroup under a
        serve.replica_drain span (the rollout timeline's drain edge)."""
        with trace.start_span(
            "serve.replica_drain",
            trace_id=self._trace_id(serve),
            attrs={
                "serve": serve.metadata.key(), "replica": rid,
                "generation": replica_generation(members) if members else -1,
                "reason": reason,
            },
        ):
            for p in members:
                self.store.try_delete("Pod", p.metadata.namespace,
                                      p.metadata.name)
            self.store.try_delete("PodGroup", serve.namespace,
                                  serve.gang_name(rid))
        # the ready-noted memo is deliberately NOT dropped here: replica
        # ids are never reused, and a cached read lagging these deletes
        # can still show the gang ready for a few reconciles — dropping
        # the mark would re-note it with its ORIGINAL creation timestamp,
        # polluting the readiness-latency histogram with a bogus
        # lifetime-length observation (caught by BENCH_CP_MODES=serve)

    def _note_ready(self, serve: TPUServe, live: Dict[int, List[Pod]],
                    ready_ids: set, gen: int) -> None:
        """First observation of a gang passing the readiness gate: the
        serve.replica_ready span + the serve-readiness latency histogram
        (creation → ready, the serving SLO the bench tripwires)."""
        now = time.time()
        for rid in sorted(ready_ids):
            mark = (serve.metadata.uid, rid)
            if mark in self._ready_noted:
                continue
            self._ready_noted.add(mark)
            created = [
                p.metadata.creation_timestamp
                for p in live.get(rid, [])
                if p.metadata.creation_timestamp
            ]
            latency = max(0.0, now - min(created)) if created else 0.0
            with trace.start_span(
                "serve.replica_ready",
                trace_id=self._trace_id(serve),
                attrs={
                    "serve": serve.metadata.key(), "replica": rid,
                    "generation": replica_generation(live.get(rid, [])),
                    "ready_latency_s": round(latency, 3),
                },
            ):
                pass
            metrics.serve_ready_latency.observe(latency)
        if len(self._ready_noted) > 8192:
            # bounded memo; a re-note after eviction is a harmless extra span
            self._ready_noted.clear()

    # ------------------------------------------------------------------
    # drain victim selection + status
    # ------------------------------------------------------------------

    @staticmethod
    def _pick_victim(live: Dict[int, List[Pod]], new_gen: set,
                     ready_ids: set, doomed: Optional[set] = None
                     ) -> Optional[int]:
        """Preference order: doomed (their node is dying — unready first),
        then unready old-gen, ready old-gen, unready newest new-gen,
        ready newest new-gen."""
        doomed = doomed or set()
        old = [rid for rid in live if rid not in new_gen and rid not in doomed]
        fresh = [rid for rid in new_gen & set(live) if rid not in doomed]
        pools = (
            (sorted(doomed & set(live)), False),
            (old, False),
            (fresh, True),
        )
        for pool, prefer_new in pools:
            if not pool:
                continue
            unready = [r for r in pool if r not in ready_ids]
            if unready:
                return max(unready) if prefer_new else min(unready)
            return max(pool) if prefer_new else min(pool)
        return None

    def _update_status(self, serve: TPUServe, live: Dict[int, List[Pod]],
                       ready_ids: set, new_gen: set, desired: int) -> None:
        st = serve.status
        st.replicas = len(live)
        st.ready_replicas = len(ready_ids)
        st.updated_replicas = len(new_gen & set(live))
        st.desired_replicas = desired
        metrics.serve_replicas_ready.set(
            st.ready_replicas, serve=serve.metadata.key()
        )
        prev = self._last_desired.get(serve.metadata.uid)
        now = time.time()
        if prev is not None and desired != prev:
            if desired > prev:
                st.last_scale_up_time = now
            else:
                st.last_scale_down_time = now
        self._last_desired[serve.metadata.uid] = desired
        if len(self._last_desired) > 4096:
            self._last_desired.pop(next(iter(self._last_desired)))

        floor = max(0, desired - serve.spec.max_unavailable)
        available = desired > 0 and st.ready_replicas >= max(1, floor)
        cond.set_condition(st, _serve_condition(
            ServeConditionType.AVAILABLE, available,
            "MinimumReplicasReady" if available else "WaitingForReplicas",
            f"{st.ready_replicas}/{desired} serving replicas ready",
        ))
        settled = (
            st.updated_replicas == desired
            and st.replicas == desired
            and st.ready_replicas >= desired
        )
        cond.set_condition(st, _serve_condition(
            ServeConditionType.PROGRESSING, not settled,
            "Rolling" if not settled else "Stable",
            (f"{st.updated_replicas}/{desired} at generation "
             f"{st.serve_generation}" if not settled
             else f"all replicas at generation {st.serve_generation}"),
        ))
        zero = desired == 0 and not live
        if zero and not cond.has_condition(
            st, ServeConditionType.SCALED_TO_ZERO
        ):
            self.recorder.event(
                serve, NORMAL, EVENT_SCALED_TO_ZERO,
                "no traffic: every serving replica released its chips",
            )
        cond.set_condition(st, _serve_condition(
            ServeConditionType.SCALED_TO_ZERO, zero,
            "NoTraffic" if zero else "Active",
            "scaled to zero" if zero else "replicas live",
        ))

    # ------------------------------------------------------------------
    # status write (uid-pinned subresource merge patch, as in batch)
    # ------------------------------------------------------------------

    def _default_write_status(self, serve: TPUServe) -> bool:
        stored = self.read.try_get("TPUServe", serve.namespace, serve.name)
        if stored is None:
            return True
        if stored.metadata.uid != serve.metadata.uid:
            return True  # recreated under us: never cross-stamp
        old, new = stored.status.to_dict(), serve.status.to_dict()
        if old == new:
            metrics.store_writes_elided.inc(component="serve-controller")
            return True
        try:
            self.store.patch(
                "TPUServe", serve.namespace, serve.name,
                {"status": diff_merge_patch(old, new),
                 "metadata": {"uid": serve.metadata.uid}},
                subresource="status",
            )
        except NotFound:
            return True
        except Conflict:
            return False
        return True


def _serve_condition(ctype: str, active: bool, reason: str, message: str):
    from mpi_operator_tpu.api.types import Condition

    return Condition.new(ctype, active, reason, message)
