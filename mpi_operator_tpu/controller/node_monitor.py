"""NodeMonitor: node-liveness controller (the kube node controller role).

The reference never detects worker loss itself — kubernetes' node controller
notices a kubelet stop posting status, marks the Node NotReady, and evicts
its pods; the MPIJob controller then sees Failed/Evicted workers and applies
its restart policy (/root/reference/v2/pkg/controller/mpi_job_controller.go
:506-529 evicted-requeue; SURVEY.md §5.3). This module is that missing first
half for this framework:

- node agents (executor/agent.py) heartbeat their Node objects;
- the monitor (run on the elected leader, opshell/__main__.py) scans them:
  a node silent past the grace window is marked NotReady and every live pod
  bound to it is force-failed with reason ``Evicted`` — which
  controller/controller.py already treats as retryable, driving the
  gang-coherent restart onto the remaining live nodes.

Nodes with ``last_heartbeat == 0`` are static (manually registered) and are
never evicted by the monitor.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from mpi_operator_tpu.machinery.events import WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import NODE_NAMESPACE, evict_pod
from mpi_operator_tpu.machinery.store import NotFound
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.nodemonitor")

EVENT_NODE_LOST = "NodeLost"


class NodeMonitor:
    def __init__(
        self,
        store,
        recorder: Optional[EventRecorder] = None,
        *,
        grace: float = 6.0,
        interval: float = 1.0,
        cache=None,
    ):
        self.store = store
        # informer read path: the per-tick Node scan (and the Pod scan when
        # nodes are stale) reads the watch-fed cache when one is wired — a
        # 1 Hz full list against the store was pure cache-miss traffic.
        # Evictions/mark-not-ready still write via optimistic re-reads.
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(
            store, component="tpujob-node-monitor"
        )
        self.grace = grace
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="node-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync()
            except Exception:
                log.exception("node monitor sync failed")  # next tick retries

    def sync(self) -> None:
        if self.cache is not None and not self.cache.has_synced():
            return  # cold cache = empty world; next tick retries
        now = time.time()
        stale = []
        for node in self.read.list("Node", NODE_NAMESPACE):
            hb = node.status.last_heartbeat
            if not hb:
                continue  # static node: no heartbeat contract
            if now - hb <= self.grace:
                continue
            stale.append(node.metadata.name)
            if node.status.ready:
                self._mark_not_ready(node.metadata.name)
                self.recorder.event(
                    node, WARNING, EVENT_NODE_LOST,
                    f"node {node.metadata.name} stopped heartbeating "
                    f"({now - hb:.1f}s > {self.grace:.1f}s grace)",
                )
                metrics.nodes_lost.inc()
                log.warning("node %s lost; evicting its pods", node.metadata.name)
        if stale:
            # ONE pod list per tick regardless of dead-node count (two
            # permanently dead nodes must not mean 2 full list round-trips
            # per second forever); level-triggered so a pod re-bound to a
            # still-dead node is caught on the next tick
            self._evict_pods(set(stale))

    def _mark_not_ready(self, name: str) -> None:
        """One status-subresource merge-patch touching ONLY ``ready``: a
        concurrent `ctl cordon` or a just-landed revival heartbeat keeps
        every field it wrote (merge semantics — the old GET+PUT loop
        re-read and retried Conflicts to achieve the same). Writes happen
        only on the ready→not-ready transition (sync() gates on
        ``node.status.ready``), so a permanently dead node costs zero
        steady-state writes."""
        try:
            self.store.patch(
                "Node", NODE_NAMESPACE, name,
                {"status": {"ready": False}}, subresource="status",
            )
        except NotFound:
            pass  # node deleted between the scan and the mark

    def _evict_pods(self, stale_nodes: set) -> None:
        for pod in self.read.list("Pod"):
            if pod.spec.node_name not in stale_nodes or pod.is_finished():
                continue
            node_name = pod.spec.node_name
            if not evict_pod(
                self.store, pod, f"node {node_name} lost (heartbeat timeout)"
            ):
                continue
            metrics.pods_evicted.inc()
            self.recorder.event(
                pod, WARNING, EVENT_NODE_LOST,
                f"evicted: node {node_name} stopped heartbeating",
            )
