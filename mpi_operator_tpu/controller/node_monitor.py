"""NodeMonitor: node-liveness controller (the kube node controller role).

The reference never detects worker loss itself — kubernetes' node controller
notices a kubelet stop posting status, marks the Node NotReady, and evicts
its pods; the MPIJob controller then sees Failed/Evicted workers and applies
its restart policy (/root/reference/v2/pkg/controller/mpi_job_controller.go
:506-529 evicted-requeue; SURVEY.md §5.3). This module is that missing first
half for this framework:

- node agents (executor/agent.py) heartbeat their Node objects;
- the monitor (run on the elected leader, opshell/__main__.py) scans them:
  a node silent past the grace window is marked NotReady and every live pod
  bound to it is force-failed with reason ``Evicted`` — which
  controller/controller.py already treats as retryable, driving the
  gang-coherent restart onto the remaining live nodes.

Nodes with ``last_heartbeat == 0`` are static (manually registered) and are
never evicted by the monitor.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.events import WARNING, EventRecorder
from mpi_operator_tpu.machinery.objects import (
    NODE_NAMESPACE,
    evict_pod,
    maintenance_at,
)
from mpi_operator_tpu.machinery.store import NotFound
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.nodemonitor")

EVENT_NODE_LOST = "NodeLost"


class NodeMonitor:
    def __init__(
        self,
        store,
        recorder: Optional[EventRecorder] = None,
        *,
        grace: float = 6.0,
        interval: float = 1.0,
        cache=None,
        defer_to_drain: bool = True,
    ):
        self.store = store
        # informer read path: the per-tick Node scan (and the Pod scan when
        # nodes are stale) reads the watch-fed cache when one is wired — a
        # 1 Hz full list against the store was pure cache-miss traffic.
        # Evictions/mark-not-ready still write via optimistic re-reads.
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(
            store, component="tpujob-node-monitor"
        )
        self.grace = grace
        self.interval = interval
        # whether a DrainController owns maintenance-noticed nodes (set
        # False when the operator runs --no-drain-controller: a notice
        # nobody will adopt must not disable node-loss eviction)
        self.defer_to_drain = defer_to_drain
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="node-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync()
            except Exception:
                log.exception("node monitor sync failed")  # next tick retries

    def sync(self) -> None:
        if self.cache is not None and not self.cache.has_synced():
            return  # cold cache = empty world; next tick retries
        now = time.time()
        stale = []
        # PER-NODE span contexts of this tick's fresh NodeLost detections:
        # each evict span below parents on the span of the node ITS pod
        # was bound to, which is how `ctl trace` attributes a gang restart
        # to the node loss that caused it (the cross-trace causal edge —
        # one lost node can hit many jobs' traces). Per node, not a single
        # last-one-wins context: two nodes dying in one tick must not
        # cross-attribute each other's evictions.
        lost_ctx = {}
        for node in self.read.list("Node", NODE_NAMESPACE):
            hb = node.status.last_heartbeat
            if not hb:
                continue  # static node: no heartbeat contract
            if now - hb <= self.grace:
                continue
            if self.defer_to_drain and maintenance_at(node) is not None:
                # a node with a VALID maintenance notice belongs to the
                # DrainController: it escalates a dead draining node to ONE
                # hard eviction itself. Evicting here too would tear the
                # same gang down twice (double restart_generation advance —
                # the double-eviction bug ISSUE 14 pins with a test), and
                # gating on the notice rather than the adopted Draining
                # condition closes the stamp-to-adopt window the same way.
                # Two escape hatches keep unplanned-loss eviction owned:
                # a MALFORMED notice (maintenance_at None) never defers,
                # and an operator running --no-drain-controller constructs
                # this monitor with defer_to_drain=False — a notice nobody
                # will ever adopt must not disable the monitor. The
                # NotReady mark below still applies: liveness is this
                # monitor's truth either way.
                if node.status.ready:
                    self._mark_not_ready(node.metadata.name)
                    log.warning(
                        "node %s lost while draining; leaving its pods to "
                        "the drain controller's escalation",
                        node.metadata.name,
                    )
                continue
            stale.append(node.metadata.name)
            if node.status.ready:
                with trace.start_span(
                    "monitor.node_lost",
                    attrs={"node": node.metadata.name,
                           "silent_s": round(now - hb, 1),
                           "grace_s": self.grace},
                ) as sp:
                    lost_ctx[node.metadata.name] = sp.context()
                    self._mark_not_ready(node.metadata.name)
                    self.recorder.event(
                        node, WARNING, EVENT_NODE_LOST,
                        f"node {node.metadata.name} stopped heartbeating "
                        f"({now - hb:.1f}s > {self.grace:.1f}s grace)",
                    )
                metrics.nodes_lost.inc()
                log.warning("node %s lost; evicting its pods", node.metadata.name)
        if stale:
            # ONE pod list per tick regardless of dead-node count (two
            # permanently dead nodes must not mean 2 full list round-trips
            # per second forever); level-triggered so a pod re-bound to a
            # still-dead node is caught on the next tick
            self._evict_pods(set(stale), lost_ctx)

    def _mark_not_ready(self, name: str) -> None:
        """One status-subresource merge-patch touching ONLY ``ready``: a
        concurrent `ctl cordon` or a just-landed revival heartbeat keeps
        every field it wrote (merge semantics — the old GET+PUT loop
        re-read and retried Conflicts to achieve the same). Writes happen
        only on the ready→not-ready transition (sync() gates on
        ``node.status.ready``), so a permanently dead node costs zero
        steady-state writes."""
        try:
            self.store.patch(
                "Node", NODE_NAMESPACE, name,
                {"status": {"ready": False}}, subresource="status",
            )
        except NotFound:
            pass  # node deleted between the scan and the mark

    def _evict_pods(self, stale_nodes: set, lost_ctx=None) -> None:
        lost_ctx = lost_ctx or {}
        for pod in self.read.list("Pod"):
            if pod.spec.node_name not in stale_nodes or pod.is_finished():
                continue
            node_name = pod.spec.node_name
            # the evict span lives in the POD's job trace (its trace-id
            # annotation) but parents on the node_lost span of the node
            # THIS pod was bound to (absent for level-triggered re-evicts
            # off a long-dead node) — that edge is the "restart generation
            # attributed to the NodeLost that caused it" `ctl trace`
            # renders
            with trace.start_span(
                "monitor.evict",
                parent=lost_ctx.get(node_name),
                trace_id=pod.metadata.annotations.get(
                    trace.ANNOTATION_TRACE_ID
                ),
                attrs={"pod": pod.metadata.key(), "node": node_name},
            ):
                if not evict_pod(
                    self.store, pod,
                    f"node {node_name} lost (heartbeat timeout)",
                ):
                    continue
                metrics.pods_evicted.inc()
                self.recorder.event(
                    pod, WARNING, EVENT_NODE_LOST,
                    f"evicted: node {node_name} stopped heartbeating",
                )
