"""The SLO plane: declarative objectives, multi-window burn-rate alerting,
and an incident flight recorder (ISSUE 13).

Before this round every p99 SLO in the repo lived only as an offline
tripwire inside ``bench_controlplane.py``, evaluated once at bench exit: a
live cluster whose reconcile p99 blew past 1 s told nobody until a human
ran ``ctl trace``. This module promotes those objectives to a runtime
alerting plane:

- **One source of SLO truth.** :func:`load_slo_config` reads the same
  declarative config file (``slo_defaults.json``) the bench tripwires
  load, and **fails closed**: an objective naming a metric family absent
  from the registry catalog, a non-histogram family under a latency
  objective, a threshold <= 0, a malformed window pair, or an unknown key
  is a load-time :class:`SLOConfigError` — never a silently-ignored
  objective. The bench's historical env override knobs
  (``BENCH_CP_SLO_*``) are preserved via each entry's ``env`` field.
- **SRE-workbook multi-window burn rates.** Each objective reduces to an
  error fraction per window (latency histograms: observations above the
  good-event bucket; gauges: scrapes above the ceiling — gauge_max — or
  below the floor — gauge_min); burn rate = error fraction / error
  budget. The alert fires when BOTH windows of a pair
  breach — fast (5m & 1h at 14.4x) pages on sudden total breaches, slow
  (30m & 6h at 6x) on sustained budget bleed — and clears only after
  every window WITH data burns below its pair's fire threshold
  continuously for the clean hold (hysteresis: a boundary-oscillating
  series cannot flap the alert). The decision core (:func:`step`) is a
  PURE function, property-swept by the test suite.
- **Alerts are store objects.** A firing writes a watchable ``Alert``
  (kind registered in serialize/cache) in the ``monitoring`` namespace;
  transitions are uid-pinned status-subresource patches and each firing
  is trace-stamped (``slo.alert`` span), so informers, ``ctl alerts``,
  and ``ctl trace --last-incident`` all see the same state.
- **Flight recorder.** Each firing dumps an incident bundle — recent
  trace spans, replica status, fair-queue/tenant counters, the last N
  watch events the monitor observed, and a scrape snapshot — under the
  incident dir; ``ctl trace --last-incident`` links it.

Runs leader-only inside the operator (``tpu-operator``), or standalone:

  python -m mpi_operator_tpu.controller.slo_monitor \\
      --store http://store:8475 \\
      --scrape-targets op=http://op:8080/metrics,s0=http://s0:9090/metrics

``--smoke`` is the <30s verify-gate check: a live 3-process wire replica
set is scraped for real while a synthetic breach is driven through the
local registry; the breach must fire (alert visible in the replicated
store) and clear.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Mapping, Optional, Tuple

from mpi_operator_tpu.api.types import (
    ALERT_NAMESPACE,
    Alert,
    AlertSpec,
    AlertState,
    AlertStatus,
    ObjectMeta,
)
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.store import AlreadyExists
from mpi_operator_tpu.machinery.telemetry import (
    INSTANCE_LABEL,
    MetricsScraper,
    ScrapeTarget,
    SeriesRing,
    parse_scrape_targets,
)
from mpi_operator_tpu.opshell import metrics as _metrics

log = logging.getLogger("tpujob.slo")

DEFAULT_CONFIG_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "slo_defaults.json"
)
ENV_SLO_CONFIG = "TPUJOB_SLO_CONFIG"
ENV_INCIDENT_DIR = "TPUJOB_INCIDENT_DIR"

# the four burn windows, in evaluation order (fast pair checked first, so
# a breach that trips both pairs is attributed to the FASTER detector)
WINDOW_KEYS = ("fast_short", "fast_long", "slow_short", "slow_long")


class SLOConfigError(ValueError):
    """A malformed SLO config — the loader's one failure mode. Fails the
    process at startup: a typo'd objective silently watching nothing
    would make every 'SLOs green' claim a lie."""


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Objective:
    """One declarative SLO. ``kind`` is 'latency' (histogram family +
    good-event bound + good-fraction target), 'gauge_max' (gauge family +
    hard ceiling + in-bounds-fraction target), or 'gauge_min' (gauge
    family + hard FLOOR: a scrape below ``bound`` is the bad event — the
    goodput-collapse shape, where low is the pathology)."""

    name: str
    metric: str
    kind: str                      # "latency" | "gauge_max" | "gauge_min"
    objective: float               # good-event fraction target (0, 1)
    threshold_s: float = 0.0       # latency: the good-event bound
    bound: float = 0.0             # gauge_max: the in-bounds ceiling
    quantile: float = 0.99         # the bench tripwire's percentile
    severity: str = "page"
    env: str = ""                  # the bench's historical override knob
    description: str = ""

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @property
    def threshold_ms(self) -> float:
        return self.threshold_s * 1e3


@dataclass(frozen=True)
class BurnPolicy:
    """The multi-window pairs + thresholds (SRE workbook ch.5 defaults)
    and the clear hysteresis. ``scaled`` compresses every window for
    benches/smokes whose whole life is seconds."""

    fast: Tuple[float, float] = (300.0, 3600.0)
    slow: Tuple[float, float] = (1800.0, 21600.0)
    burn_fast: float = 14.4
    burn_slow: float = 6.0
    clear_hold_s: float = 300.0

    def windows(self) -> Dict[str, float]:
        return {
            "fast_short": self.fast[0], "fast_long": self.fast[1],
            "slow_short": self.slow[0], "slow_long": self.slow[1],
        }

    def scaled(self, scale: float) -> "BurnPolicy":
        if scale <= 0:
            raise SLOConfigError(f"window scale must be > 0, got {scale}")
        return replace(
            self,
            fast=(self.fast[0] * scale, self.fast[1] * scale),
            slow=(self.slow[0] * scale, self.slow[1] * scale),
            clear_hold_s=self.clear_hold_s * scale,
        )


@dataclass(frozen=True)
class SLOConfig:
    objectives: Tuple[Objective, ...]
    policy: BurnPolicy
    path: str = ""

    def objective(self, name: str) -> Objective:
        for o in self.objectives:
            if o.name == name:
                return o
        raise KeyError(f"no SLO objective named {name!r}")

    def threshold_ms(self, name: str, *, scale: float = 1.0,
                     env: Optional[Mapping[str, str]] = None) -> float:
        """The bench-tripwire read: objective's latency bound in ms with
        the env override applied LAST (so a deployment knob beats both
        the file and any bench scaling) — the single-source-of-truth
        contract between bench and monitor."""
        o = self.objective(name)
        base = (o.threshold_ms if o.kind == "latency" else o.bound) * scale
        env = os.environ if env is None else env
        if o.env and env.get(o.env):
            return float(env[o.env])
        return base

    def scaled(self, scale: float) -> "SLOConfig":
        return replace(self, policy=self.policy.scaled(scale))


_OBJECTIVE_KEYS = {
    "name", "metric", "kind", "objective", "threshold_ms", "bound",
    "quantile", "severity", "env", "description",
}
_TOP_KEYS = {"_comment", "windows", "burn", "clear_hold_s", "objectives"}


def _window_pair(raw: Any, which: str) -> Tuple[float, float]:
    if (not isinstance(raw, (list, tuple)) or len(raw) != 2
            or not all(isinstance(v, (int, float)) for v in raw)):
        raise SLOConfigError(
            f"windows.{which} must be [short_s, long_s], got {raw!r}")
    short, long_ = float(raw[0]), float(raw[1])
    if short <= 0 or long_ <= 0 or short >= long_:
        raise SLOConfigError(
            f"windows.{which}: need 0 < short < long, got {raw!r}")
    return (short, long_)


def load_slo_config(
    path: Optional[str] = None, *,
    registry: "_metrics.Registry" = _metrics.REGISTRY,
    env: Optional[Mapping[str, str]] = None,
    window_scale: float = 1.0,
) -> SLOConfig:
    """Load + validate the SLO config, FAIL CLOSED on anything off:
    unknown top-level/objective keys, objectives naming metric families
    absent from the registry catalog, kind/instrument mismatches, bad
    thresholds or targets, malformed/inverted window pairs, duplicate
    names. Env overrides (each entry's ``env`` knob) apply to thresholds
    at load, so monitor and bench read identical numbers."""
    env = os.environ if env is None else env
    path = path or env.get(ENV_SLO_CONFIG) or DEFAULT_CONFIG_PATH
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise SLOConfigError(f"cannot read SLO config {path}: {e}") from None
    except ValueError as e:
        raise SLOConfigError(f"SLO config {path} is not JSON: {e}") from None
    if not isinstance(doc, dict):
        raise SLOConfigError(f"SLO config {path}: top level must be an object")
    unknown = set(doc) - _TOP_KEYS
    if unknown:
        raise SLOConfigError(
            f"SLO config {path}: unknown top-level keys {sorted(unknown)}")

    windows = doc.get("windows", {})
    if not isinstance(windows, dict) or set(windows) - {"fast", "slow"}:
        raise SLOConfigError(
            f"SLO config {path}: 'windows' must be "
            f"{{'fast': [s,l], 'slow': [s,l]}}")
    burn = doc.get("burn", {})
    if not isinstance(burn, dict) or set(burn) - {"fast", "slow"}:
        raise SLOConfigError(f"SLO config {path}: 'burn' must be "
                             f"{{'fast': x, 'slow': y}}")
    policy = BurnPolicy()
    if "fast" in windows:
        policy = replace(policy, fast=_window_pair(windows["fast"], "fast"))
    if "slow" in windows:
        policy = replace(policy, slow=_window_pair(windows["slow"], "slow"))
    for which in ("fast", "slow"):
        if which in burn:
            v = burn[which]
            if not isinstance(v, (int, float)) or v <= 0:
                raise SLOConfigError(
                    f"SLO config {path}: burn.{which} must be > 0, got {v!r}")
            policy = replace(policy, **{f"burn_{which}": float(v)})
    hold = doc.get("clear_hold_s", policy.clear_hold_s)
    if not isinstance(hold, (int, float)) or hold < 0:
        raise SLOConfigError(
            f"SLO config {path}: clear_hold_s must be >= 0, got {hold!r}")
    policy = replace(policy, clear_hold_s=float(hold))

    raw_objs = doc.get("objectives")
    if not isinstance(raw_objs, list) or not raw_objs:
        raise SLOConfigError(
            f"SLO config {path}: 'objectives' must be a non-empty list")
    catalog = set(registry.names())
    objectives: List[Objective] = []
    seen = set()
    for i, o in enumerate(raw_objs):
        where = f"SLO config {path}: objectives[{i}]"
        if not isinstance(o, dict):
            raise SLOConfigError(f"{where}: must be an object")
        unknown = set(o) - _OBJECTIVE_KEYS
        if unknown:
            raise SLOConfigError(f"{where}: unknown keys {sorted(unknown)}")
        name = o.get("name")
        metric = o.get("metric")
        kind = o.get("kind")
        if not name or not isinstance(name, str):
            raise SLOConfigError(f"{where}: 'name' is required")
        if name in seen:
            raise SLOConfigError(f"{where}: duplicate objective {name!r}")
        seen.add(name)
        if not metric or not isinstance(metric, str):
            raise SLOConfigError(f"{where} ({name}): 'metric' is required")
        if metric not in catalog:
            raise SLOConfigError(
                f"{where} ({name}): metric {metric!r} is not in the "
                f"registry catalog — an objective on an unregistered "
                f"family would silently watch nothing (oplint OBS003 "
                f"catches this at diff time)")
        inst_kind = registry.kind_of(metric)
        if kind == "latency":
            if inst_kind != "histogram":
                raise SLOConfigError(
                    f"{where} ({name}): latency objectives need a "
                    f"histogram family; {metric} is a {inst_kind}")
            thr = o.get("threshold_ms")
            if not isinstance(thr, (int, float)) or thr <= 0:
                raise SLOConfigError(
                    f"{where} ({name}): threshold_ms must be > 0, "
                    f"got {thr!r}")
        elif kind in ("gauge_max", "gauge_min"):
            if inst_kind != "gauge":
                raise SLOConfigError(
                    f"{where} ({name}): {kind} objectives need a "
                    f"gauge family; {metric} is a {inst_kind}")
            bnd = o.get("bound")
            if not isinstance(bnd, (int, float)) or bnd <= 0:
                raise SLOConfigError(
                    f"{where} ({name}): bound must be > 0, got {bnd!r}")
        else:
            raise SLOConfigError(
                f"{where} ({name}): unknown kind {kind!r} "
                f"(latency | gauge_max | gauge_min)")
        target = o.get("objective")
        if not isinstance(target, (int, float)) or not 0.0 < target < 1.0:
            raise SLOConfigError(
                f"{where} ({name}): 'objective' must be in (0, 1), "
                f"got {target!r}")
        q = o.get("quantile", 0.99)
        if not isinstance(q, (int, float)) or not 0.0 < q < 1.0:
            raise SLOConfigError(
                f"{where} ({name}): 'quantile' must be in (0, 1)")
        sev = o.get("severity", "page")
        if sev not in ("page", "ticket"):
            raise SLOConfigError(
                f"{where} ({name}): severity must be page|ticket, got {sev!r}")
        thr_ms = float(o.get("threshold_ms") or 0.0)
        bound = float(o.get("bound") or 0.0)
        env_key = o.get("env") or ""
        # env override: a deployment's exported knob beats the file value
        # for BOTH the monitor and the bench tripwire (same loader)
        if env_key and env.get(env_key):
            try:
                v = float(env[env_key])
            except ValueError:
                raise SLOConfigError(
                    f"{where} ({name}): env override {env_key}="
                    f"{env[env_key]!r} is not a number") from None
            if v <= 0:
                raise SLOConfigError(
                    f"{where} ({name}): env override {env_key} must be > 0")
            if kind == "latency":
                thr_ms = v
            else:
                bound = v
        objectives.append(Objective(
            name=name, metric=metric, kind=kind, objective=float(target),
            threshold_s=thr_ms / 1e3, bound=bound, quantile=float(q),
            severity=sev, env=env_key, description=o.get("description", ""),
        ))
    cfg = SLOConfig(tuple(objectives), policy, path=path)
    return cfg.scaled(window_scale) if window_scale != 1.0 else cfg


# ---------------------------------------------------------------------------
# the pure burn-rate core (property-swept; no clocks, no I/O)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Probe:
    """One objective's alert state between ticks — immutable, so the
    decision core stays a pure (state, inputs) -> (state, event) map."""

    firing: bool = False
    window: str = ""               # "fast" | "slow" while firing
    since: float = 0.0
    worst_burn: float = 0.0
    clean_since: Optional[float] = None
    fired_count: int = 0


FIRE = "fire"
RESOLVE = "resolve"


def burn_rates(error_fractions: Mapping[str, Optional[float]],
               budget: float) -> Dict[str, Optional[float]]:
    """error fraction per window -> budget-burn multiple per window
    (None = no data in that window, which never breaches)."""
    b = max(1e-9, budget)
    return {
        k: (None if v is None else v / b)
        for k, v in error_fractions.items()
    }


def step(state: Probe, burns: Mapping[str, Optional[float]],
         policy: BurnPolicy, now: float) -> Tuple[Probe, Optional[str]]:
    """One evaluation tick of the multi-window burn-rate machine.

    Fire: BOTH windows of a pair exceed the pair's burn threshold (fast
    checked first — a breach tripping both is attributed to the faster
    detector). A single-sample blip cannot fire: the long window of the
    pair must agree, which is the multi-window design's whole point.

    Clear: while firing, every window that HAS data must burn below its
    pair's fire threshold, continuously for ``clear_hold_s`` — the clean
    hold is the hysteresis: a series oscillating across the fire
    threshold re-arms the hold on every suspect tick, so the alert stays
    FIRING through the flap instead of paging on every crossing; and
    since clearing itself consumed a clean window, a cleared alert can
    only re-fire after one. Data gaps are judged asymmetrically: a
    window with NO data never *fires* (a dead workload emits nothing),
    but while firing, an all-silent tick HOLDS state rather than
    progressing the clean hold — zero completions mid-incident usually
    means things are stalled, not healed (the bench's injected-latency
    fault makes short windows gap exactly this way)."""

    def pair_breach(short: str, long_: str, thr: float) -> bool:
        s, l = burns.get(short), burns.get(long_)
        return s is not None and l is not None and s > thr and l > thr

    def any_hot(short: str, long_: str, thr: float) -> bool:
        return any(
            b is not None and b > thr
            for b in (burns.get(short), burns.get(long_))
        )

    breach_fast = pair_breach("fast_short", "fast_long", policy.burn_fast)
    breach_slow = pair_breach("slow_short", "slow_long", policy.burn_slow)
    observed = [b for b in burns.values() if b is not None]
    worst = max(observed) if observed else 0.0

    if not state.firing:
        if breach_fast or breach_slow:
            return Probe(
                firing=True,
                window="fast" if breach_fast else "slow",
                since=now,
                worst_burn=worst,
                clean_since=None,
                fired_count=state.fired_count + 1,
            ), FIRE
        return replace(state, worst_burn=worst, clean_since=None), None

    # firing: track the worst burn, wait for the clean hold
    worst = max(worst, state.worst_burn)
    suspect = (any_hot("fast_short", "fast_long", policy.burn_fast)
               or any_hot("slow_short", "slow_long", policy.burn_slow))
    if suspect:
        return replace(state, worst_burn=worst, clean_since=None), None
    if not observed:
        # all windows silent: indeterminate — neither clean progress nor
        # a reset (the clean hold resumes where it was once data returns)
        return replace(state, worst_burn=worst), None
    clean_since = state.clean_since if state.clean_since is not None else now
    if now - clean_since >= policy.clear_hold_s:
        return replace(
            state, firing=False, clean_since=None, worst_burn=worst,
        ), RESOLVE
    return replace(state, worst_burn=worst, clean_since=clean_since), None


def error_fractions(ring: SeriesRing, obj: Objective, policy: BurnPolicy,
                    now: float, **labels: str) -> Dict[str, Optional[float]]:
    """Per-window error fractions for one objective out of the scraped
    ring — the impure half the pure core consumes. Latency: fraction of
    window observations above the good-event bucket. Gauge: the WORST
    matching series' fraction of in-window scrapes out of bounds —
    above the ceiling for gauge_max, below the floor for gauge_min (one
    collapsed job among a healthy fleet must still burn)."""
    out: Dict[str, Optional[float]] = {}
    for key, window in policy.windows().items():
        if obj.kind == "latency":
            out[key] = ring.error_fraction(
                obj.metric, obj.threshold_s, window, now, **labels)
        else:
            worst: Optional[float] = None
            for _, vals in ring.window_values(obj.metric, window, now,
                                              **labels):
                if obj.kind == "gauge_min":
                    bad = sum(1 for v in vals if v < obj.bound)
                else:
                    bad = sum(1 for v in vals if v > obj.bound)
                frac = bad / len(vals)
                worst = frac if worst is None else max(worst, frac)
            out[key] = worst
    return out


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Dumps the incident bundle a firing alert triggers: enough context
    to start triage without a live cluster — recent trace spans, replica
    status, fair-queue/tenant counters, the last N watch events the
    monitor observed, and the scrape-health snapshot. One JSON file per
    firing under ``dir``; ``ctl trace --last-incident`` links the newest."""

    SPAN_TAIL = 200
    EVENT_TAIL = 50

    def __init__(self, dir: str):
        self.dir = dir

    @staticmethod
    def newest_bundle(dir: str) -> Optional[str]:
        try:
            names = [n for n in os.listdir(dir)
                     if n.startswith("incident-") and n.endswith(".json")]
        except OSError:
            return None
        if not names:
            return None
        return os.path.join(dir, max(names))

    def dump(self, *, alert: Alert, burns: Mapping[str, Optional[float]],
             scraper: Optional[MetricsScraper], store: Any,
             watch_tail: Optional[List[Dict[str, Any]]] = None,
             now: Optional[float] = None) -> Optional[str]:
        now = time.time() if now is None else now
        bundle: Dict[str, Any] = {
            "version": 1,
            "at": now,
            "objective": alert.spec.objective,
            "alert": alert.to_dict(),
            "burns": {k: v for k, v in burns.items() if v is not None},
        }
        if scraper is not None:
            bundle["scrape"] = {
                "targets": [{"instance": t.instance, "url": t.url}
                            for t in scraper.targets],
                "errors": {k: v for k, v in scraper.last_error.items() if v},
                "series": scraper.ring.series_count(),
                "tenant_queued": [
                    {"labels": lbl, "value": v}
                    for lbl, _, v in scraper.ring.latest(
                        "tpu_operator_store_tenant_queued_total")
                ],
                "tenant_rejected": [
                    {"labels": lbl, "value": v}
                    for lbl, _, v in scraper.ring.latest(
                        "tpu_operator_store_tenant_rejected_total")
                ],
            }
        if watch_tail:
            bundle["watch_events"] = watch_tail[-self.EVENT_TAIL:]
        # recent spans: the in-process ring plus (when exporting) the
        # merged on-disk tail — the causal neighborhood of the breach
        spans = trace.TRACER.ring()
        if trace.TRACER._dir:
            try:
                spans = trace.load_spans(trace.TRACER._dir)
            except OSError:
                log.debug("span merge for bundle failed", exc_info=True)
        bundle["spans"] = spans[-self.SPAN_TAIL:]
        if store is not None:
            status_fn = getattr(store, "replica_status", None)
            if callable(status_fn):
                try:
                    bundle["replica_status"] = status_fn()
                except Exception as e:
                    log.debug("bundle replica status failed", exc_info=True)
                    bundle["replica_status_error"] = str(e)
            try:
                evs = store.list("Event")
                evs.sort(key=lambda e: e.timestamp)
                bundle["events"] = [
                    {"age_s": round(now - e.timestamp, 1), "type": e.type,
                     "reason": e.reason,
                     "involved": f"{e.involved.kind}/"
                                 f"{e.involved.namespace}/{e.involved.name}",
                     "message": e.message}
                    for e in evs[-self.EVENT_TAIL:]
                ]
            except Exception as e:
                log.debug("bundle event tail failed", exc_info=True)
                bundle["events_error"] = str(e)
        name = (f"incident-{time.strftime('%Y%m%d-%H%M%S', time.gmtime(now))}"
                f"-{alert.spec.objective}.json")
        path = os.path.join(self.dir, name)
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=1, default=str)
            os.replace(tmp, path)  # readers never see a torn bundle
        except OSError:
            # a full disk must not take the alerting plane down with it
            log.warning("flight recorder dump failed", exc_info=True)
            return None
        return path


# ---------------------------------------------------------------------------
# the monitor shell
# ---------------------------------------------------------------------------


class SLOMonitor:
    """Scrape → evaluate → alert, one pass per ``interval``. Writes Alert
    objects into the store (leader-only when embedded in the operator:
    two monitors racing would flap each other's uid-pinned patches)."""

    def __init__(self, store: Any, targets: List[ScrapeTarget],
                 config: SLOConfig, *, interval: float = 15.0,
                 scrape_timeout: float = 5.0,
                 incident_dir: Optional[str] = None,
                 watch_tail: int = 64,
                 ring: Optional[SeriesRing] = None):
        self.store = store
        self.config = config
        self.interval = interval
        if ring is None:
            # the ring must hold the LONGEST burn window's worth of
            # scrapes or the slow pair silently evaluates a truncated
            # window (at the 15s default the 6h slow_long needs ~1440
            # samples — the 512 default would quietly judge ~2.1h)
            need = int(max(config.policy.slow[1], config.policy.fast[1])
                       / max(1e-6, interval)) + 8
            ring = SeriesRing(capacity=max(512, need))
        self.scraper = MetricsScraper(
            targets, ring=ring, interval=interval, timeout=scrape_timeout)
        d = incident_dir or os.environ.get(ENV_INCIDENT_DIR)
        if not d and os.environ.get(trace.ENV_TRACE_DIR):
            d = os.path.join(os.environ[trace.ENV_TRACE_DIR], "incidents")
        self.recorder = FlightRecorder(d) if d else None
        self.states: Dict[str, Probe] = {
            o.name: Probe() for o in config.objectives
        }
        # objective → alert state last successfully WRITTEN to the store
        # ("Firing"/"Resolved"); a write that failed (store failing over
        # — exactly when alerts matter most) leaves this stale and the
        # next tick retries until store and monitor agree
        self._written: Dict[str, str] = {}
        # objective → (firing since, trace id, bundle path): one slo.alert
        # span + ONE flight-recorder dump per firing — write RETRIES reuse
        # them instead of minting a fresh trace and bundle every tick a
        # downed store refuses the write
        self._firing_ctx: Dict[str, Tuple[float, str, str]] = {}
        # objectives whose durable store state has not been adopted yet
        # (leader-restart continuity); an unreadable alert stays pending
        # and is retried next tick — a store mid-failover at the new
        # leader's FIRST tick must not permanently skip adoption
        self._adopt_pending = {o.name for o in config.objectives}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watch_tail: deque = deque(maxlen=watch_tail)
        self._watch_q = None
        self._watch_thread: Optional[threading.Thread] = None

    # -- the watch tail (flight-recorder context) ----------------------------

    def _drain_watch(self) -> None:
        import queue as _queue

        while not self._stop.is_set():
            try:
                ev = self._watch_q.get(timeout=0.25)
            except _queue.Empty:
                continue
            if ev is None:
                break
            try:
                m = ev.obj.metadata
                self._watch_tail.append({
                    "t": round(time.time(), 3), "type": ev.type,
                    "kind": ev.obj.kind, "key": f"{m.namespace}/{m.name}",
                    "rv": m.resource_version,
                })
            # oplint: disable=EXC001 — a malformed event must not kill
            # the tail thread; the tail is best-effort triage context
            except Exception:
                pass

    # -- one pass ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Probe]:
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        with trace.start_span("monitor.sync"):
            if self._adopt_pending:
                self._adopt_store_state(now)
            self.scraper.scrape_once(now)
            for obj in self.config.objectives:
                fracs = error_fractions(
                    self.scraper.ring, obj, self.config.policy, now)
                burns = burn_rates(fracs, obj.budget)
                state, event = step(
                    self.states[obj.name], burns, self.config.policy, now)
                self.states[obj.name] = state
                # write-reconciliation, not edge-triggering: a transition
                # whose store write failed (421 mid-failover, 503) is
                # retried every tick until the store agrees
                desired = (AlertState.FIRING if state.firing
                           else AlertState.RESOLVED if state.fired_count
                           else None)
                if event == FIRE:
                    _metrics.slo_alerts_fired.inc(objective=obj.name)
                    if self.store is None:
                        # storeless mode (tpu-monitor --once/no --store):
                        # evaluate+log only, nothing to reconcile against
                        log.warning(
                            "SLO alert FIRING (no store configured): "
                            "%s burning %.1fx (%s windows)", obj.name,
                            state.worst_burn, state.window)
                elif event == RESOLVE and self.store is None:
                    log.warning("SLO alert resolved (no store "
                                "configured): %s", obj.name)
                if desired is not None and self.store is not None \
                        and self._written.get(obj.name) != desired:
                    if desired == AlertState.FIRING:
                        self._fire(obj, state, burns, now)
                    else:
                        self._resolve(obj, state, now)
        _metrics.monitor_tick_latency.observe(time.perf_counter() - t0)
        return dict(self.states)

    # -- alert writes (uid-pinned status patches) ----------------------------

    def _adopt_store_state(self, now: float) -> None:
        """Leader failover restarts the monitor with fresh in-memory
        state; adopt the store's durable Alert objects so (a) an alert
        the previous leader left Firing resolves when its breach heals
        instead of sticking forever, and (b) a refire CONTINUES the
        durable fired_count recurrence record instead of restarting at
        1. An objective whose alert is UNREADABLE (store mid-failover —
        precisely when leaders change) stays pending and is retried
        next tick; once the local probe has evolved on its own, the
        local state wins (adoption must never clobber live decisions)."""
        if self.store is None:
            self._adopt_pending.clear()
            return
        for name in sorted(self._adopt_pending):
            obj = self.config.objective(name)
            if self.states[name] != Probe():
                # local evaluation already moved this objective: too
                # late to adopt without clobbering a live decision
                self._adopt_pending.discard(name)
                continue
            ok, alert = self._get_alert(obj.name)
            if not ok:
                log.warning("alert-state adoption: %s unreadable; "
                            "retrying next tick", obj.name)
                continue
            self._adopt_pending.discard(name)
            if alert is None:
                continue
            st = alert.status
            if alert.is_firing():
                self.states[obj.name] = Probe(
                    firing=True, window=st.window or "fast",
                    since=st.since or now, worst_burn=st.burn,
                    fired_count=max(1, st.fired_count),
                )
                self._written[obj.name] = AlertState.FIRING
                # retries must not re-dump the previous leader's incident
                self._firing_ctx[obj.name] = (
                    st.since or now,
                    alert.metadata.annotations.get(
                        trace.ANNOTATION_TRACE_ID, ""),
                    st.incident,
                )
                _metrics.slo_alerts_firing.set(1, objective=obj.name)
                log.warning("adopted FIRING alert %s from the store "
                            "(fired_count=%d)", obj.name,
                            st.fired_count)
            else:
                self.states[obj.name] = Probe(
                    fired_count=max(1, st.fired_count))
                self._written[obj.name] = AlertState.RESOLVED

    def _fire(self, obj: Objective, state: Probe,
              burns: Mapping[str, Optional[float]], now: float) -> None:
        """Write the FIRING state into the store. Retried by tick()'s
        write-reconciliation until it lands — a store mid-failover (very
        plausibly the incident itself) must not lose the page. The
        slo.alert span and the flight-recorder bundle are minted ONCE
        per firing (keyed by the probe's fire time); retries reuse them."""
        msg = (f"{obj.metric} burning {state.worst_burn:.1f}x its "
               f"{obj.budget:.2%} error budget ({state.window} windows)")
        ctx = self._firing_ctx.get(obj.name)
        if ctx is None or ctx[0] != state.since:
            log.warning("SLO alert FIRING: %s — %s", obj.name, msg)
            preview = self._new_alert(obj)
            preview.status = AlertStatus(
                state=AlertState.FIRING, window=state.window,
                burn=round(state.worst_burn, 3), since=state.since,
                message=msg, fired_count=state.fired_count,
            )
            with trace.start_span(
                "slo.alert", parent=trace.ROOT,
                attrs={"objective": obj.name, "window": state.window,
                       "burn": round(state.worst_burn, 2),
                       "severity": obj.severity},
            ) as sp:
                bundle = ""
                if self.recorder is not None:
                    bundle = self.recorder.dump(
                        alert=preview, burns=burns, scraper=self.scraper,
                        store=self.store,
                        watch_tail=list(self._watch_tail), now=now,
                    ) or ""
                    sp.set_attr("bundle", bundle)
                ctx = (state.since, sp.trace_id or "", bundle)
            self._firing_ctx[obj.name] = ctx
        _, tid, bundle = ctx
        status = AlertStatus(
            state=AlertState.FIRING, window=state.window,
            burn=round(state.worst_burn, 3), since=state.since,
            message=msg, fired_count=state.fired_count, incident=bundle,
        )
        ok, alert = self._get_alert(obj.name)
        if not ok:
            return  # store unreadable: next tick retries
        if alert is None:
            obj_new = self._new_alert(obj)
            obj_new.status = status
            obj_new.metadata.annotations[trace.ANNOTATION_TRACE_ID] = tid
            try:
                self.store.create(obj_new)
                self._written[obj.name] = AlertState.FIRING
                _metrics.slo_alerts_firing.set(1, objective=obj.name)
                return
            except AlreadyExists:
                ok, alert = self._get_alert(obj.name)  # raced another fire
                if not ok or alert is None:
                    return
            except Exception as e:
                # a failing store (possibly the very incident being
                # alerted) — _written stays stale, next tick retries
                log.warning("alert create failed (will retry): %s", e)
                return
        try:
            # each firing is its own trace: re-stamp the annotation
            # (plain patch; identity frozen), then the uid-pinned
            # status transition
            self.store.patch(
                "Alert", ALERT_NAMESPACE, obj.name,
                {"metadata": {
                    "uid": alert.metadata.uid,
                    "annotations": {trace.ANNOTATION_TRACE_ID: tid},
                }},
            )
            status_patch = status.to_dict()
            # merge-patch null: a refire must CLEAR the previous
            # resolution stamp (to_dict prunes Nones, so set it
            # explicitly — json-merge-patch deletes on null)
            status_patch["resolved_at"] = None
            self.store.patch(
                "Alert", ALERT_NAMESPACE, obj.name,
                {"metadata": {"uid": alert.metadata.uid},
                 "status": status_patch},
                subresource="status",
            )
            self._written[obj.name] = AlertState.FIRING
            _metrics.slo_alerts_firing.set(1, objective=obj.name)
        except Exception as e:
            log.warning("alert fire patch failed (will retry): %s", e)

    def _resolve(self, obj: Objective, state: Probe, now: float) -> None:
        ok, alert = self._get_alert(obj.name)
        if not ok:
            return  # read failed ≠ alert gone: next tick retries
        if alert is None:
            # deleted out from under us: nothing left to resolve, but
            # the monitor's OWN exports must still drop the firing
            # (a phantom 1 on the gauge would page forever)
            self._written[obj.name] = AlertState.RESOLVED
            self._firing_ctx.pop(obj.name, None)
            _metrics.slo_alerts_firing.set(0, objective=obj.name)
            return
        log.warning("SLO alert resolved: %s (worst burn %.1fx)",
                    obj.name, state.worst_burn)
        try:
            self.store.patch(
                "Alert", ALERT_NAMESPACE, obj.name,
                {"metadata": {"uid": alert.metadata.uid},
                 "status": {"state": AlertState.RESOLVED,
                            "resolved_at": now,
                            "message": f"clean for "
                                       f"{self.config.policy.clear_hold_s:g}s"
                                       f" after burning "
                                       f"{state.worst_burn:.1f}x"}},
                subresource="status",
            )
            self._written[obj.name] = AlertState.RESOLVED
            self._firing_ctx.pop(obj.name, None)
            _metrics.slo_alerts_firing.set(0, objective=obj.name)
        except Exception as e:
            log.warning("alert resolve patch failed (will retry): %s", e)

    def _get_alert(self, name: str) -> Tuple[bool, Optional[Alert]]:
        """(read_ok, alert). A read FAILURE is not the same claim as
        'no alert': callers must retry on (False, None), never conclude
        the alert was deleted (that conclusion once marked a resolve as
        written and left the store's page stuck Firing forever)."""
        try:
            return True, self.store.try_get("Alert", ALERT_NAMESPACE, name)
        except Exception as e:
            log.warning("alert read failed: %s", e)
            return False, None

    def _new_alert(self, obj: Objective) -> Alert:
        return Alert(
            metadata=ObjectMeta(name=obj.name, namespace=ALERT_NAMESPACE),
            spec=AlertSpec(
                objective=obj.name, metric=obj.metric,
                severity=obj.severity, description=obj.description,
            ),
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "SLOMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        if self.store is not None and self._watch_q is None:
            try:
                self._watch_q = self.store.watch(None)
                self._watch_thread = threading.Thread(
                    target=self._drain_watch, name="slo-watch-tail",
                    daemon=True)
                self._watch_thread.start()
            except Exception as e:
                log.warning("watch tail unavailable: %s", e)
                self._watch_q = None
        self._thread = threading.Thread(
            target=self._run, name="slo-monitor", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            # oplint: disable=EXC001 — one bad pass (store blip mid-
            # failover) must not kill the alerting plane; errors are
            # logged and the next tick retries
            except Exception:
                log.exception("SLO monitor tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._watch_q is not None:
            try:
                self.store.stop_watch(self._watch_q)
            except Exception as e:
                log.debug("stop_watch failed: %s", e)
            self._watch_q.put(None)
            self._watch_q = None
        for t in (self._thread, self._watch_thread):
            if t is not None:
                t.join(timeout=2.0)
        self._thread = self._watch_thread = None


# ---------------------------------------------------------------------------
# standalone entry point (tpu-monitor) + the verify-gate smoke
# ---------------------------------------------------------------------------


def build_monitor(store: Any, *, scrape_targets: str = "",
                  slo_config: Optional[str] = None,
                  interval: float = 15.0, window_scale: float = 1.0,
                  incident_dir: Optional[str] = None,
                  extra_targets: Optional[List[ScrapeTarget]] = None,
                  ) -> SLOMonitor:
    """The one construction path operator main, tpu-monitor, and the
    bench share (flag parsing → validated config → monitor)."""
    targets = list(extra_targets or [])
    targets.extend(parse_scrape_targets(scrape_targets))
    if not targets:
        targets = [ScrapeTarget("self", "self")]
    config = load_slo_config(slo_config, window_scale=window_scale)
    return SLOMonitor(store, targets, config, interval=interval,
                      incident_dir=incident_dir)


def smoke() -> int:
    """The <30s verify-gate monitor smoke: spin a live 3-process wire
    replica set (each exporting /metrics), scrape all three PLUS this
    process for real, drive a synthetic breach through the local
    registry (slow observations into the reconcile histogram), and
    assert the matching alert FIRES into the replicated store, carries a
    flight-recorder bundle, and CLEARS once the breach stops. Prints one
    JSON line; exit 0 iff every bar held."""
    import shutil
    import subprocess
    import sys
    import tempfile

    from mpi_operator_tpu.machinery.http_store import HttpStoreClient
    from mpi_operator_tpu.machinery.replica_wire import (
        free_ports,
        wait_for_wire_leader,
    )

    tmp = tempfile.mkdtemp(prefix="slo-smoke-")
    ids = ["n0", "n1", "n2"]
    ports = free_ports(6)
    store_ports = dict(zip(ids, ports[:3]))
    mon_ports = dict(zip(ids, ports[3:]))
    urls = {nid: f"http://127.0.0.1:{store_ports[nid]}" for nid in ids}
    tok = os.path.join(tmp, "peer.token")
    with open(tok, "w") as f:
        f.write("smoke-peer\n")
    peers = ",".join(f"{nid}={urls[nid]}" for nid in ids)
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    procs = {}
    out: Dict[str, Any] = {"metric": "slo_monitor_smoke", "ok": False}
    client = None
    monitor = None
    t_start = time.time()
    # the firing must be trace-stamped (the smoke's trace_stamped bar):
    # export spans like a real deployment would
    trace.TRACER.configure("monitor-smoke",
                           dir=os.path.join(tmp, "traces"))
    try:
        for nid in ids:
            procs[nid] = subprocess.Popen(
                [sys.executable, "-m", "mpi_operator_tpu.machinery.http_store",
                 "--store", f"sqlite:{os.path.join(tmp, nid + '.db')}",
                 "--listen", f"127.0.0.1:{store_ports[nid]}",
                 "--replica-id", nid, "--peers", peers,
                 "--peer-token-file", tok,
                 "--monitoring-port", str(mon_ports[nid]),
                 "--replica-lease-duration", "1.0",
                 "--replica-retry-period", "0.1"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        leader = wait_for_wire_leader(urls, 20.0)
        if leader is None:
            out["error"] = "no wire leader"
            return _smoke_emit(out)
        client = HttpStoreClient(list(urls.values()), timeout=10.0,
                                 conn_refused_retries=10)
        targets = [ScrapeTarget("smoke", "self")] + [
            ScrapeTarget(nid,
                         f"http://127.0.0.1:{mon_ports[nid]}/metrics")
            for nid in ids
        ]
        config = load_slo_config().scaled(1.0 / 300.0)  # fast (1s, 12s)
        monitor = SLOMonitor(client, targets, config, interval=0.25,
                             incident_dir=os.path.join(tmp, "incidents"))
        # the synthetic breach: every reconcile "takes" 3s (> the 1s
        # good-event bound) — written into the LOCAL registry the 'smoke'
        # target scrapes, exactly how a real regression would look
        def observe(bad: bool, n: int = 10) -> None:
            for _ in range(n):
                _metrics.reconcile_latency.observe(3.0 if bad else 0.002)

        fired_at = resolved_at = None
        deadline = time.time() + 12.0
        while time.time() < deadline and fired_at is None:
            observe(bad=True)
            monitor.tick()
            a = client.try_get("Alert", ALERT_NAMESPACE, "reconcile-latency")
            if a is not None and a.is_firing():
                fired_at = time.time()
            time.sleep(0.25)
        out["fired"] = fired_at is not None
        if fired_at is None:
            out["error"] = "breach never fired"
            return _smoke_emit(out)
        alert = client.get("Alert", ALERT_NAMESPACE, "reconcile-latency")
        out["window"] = alert.status.window
        out["bundle"] = bool(alert.status.incident
                             and os.path.exists(alert.status.incident))
        out["trace_stamped"] = bool(
            alert.metadata.annotations.get(trace.ANNOTATION_TRACE_ID))
        out["replicas_scraped"] = sorted(
            lbl[INSTANCE_LABEL]
            for lbl, _, v in monitor.scraper.ring.latest("up") if v == 1.0
        )
        # heal: fast, clean observations until every window drains
        deadline = time.time() + 16.0
        while time.time() < deadline and resolved_at is None:
            observe(bad=False)
            monitor.tick()
            a = client.get("Alert", ALERT_NAMESPACE, "reconcile-latency")
            if a.status.state == AlertState.RESOLVED:
                resolved_at = time.time()
            time.sleep(0.25)
        out["resolved"] = resolved_at is not None
        out["elapsed_s"] = round(time.time() - t_start, 1)
        out["ok"] = bool(
            out["fired"] and out["resolved"] and out["bundle"]
            and out["trace_stamped"]
            and len(out["replicas_scraped"]) == 4  # 3 replicas + self
        )
        return _smoke_emit(out)
    finally:
        trace.TRACER.disable()
        if monitor is not None:
            monitor.stop()
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _smoke_emit(out: Dict[str, Any]) -> int:
    print(json.dumps(out), flush=True)
    return 0 if out.get("ok") else 1


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="tpu-monitor",
        description="Standalone SLO monitor: scrape the fleet's /metrics, "
                    "evaluate burn-rate objectives, write Alert objects "
                    "into the store, dump incident bundles.",
    )
    ap.add_argument("--store", default=None,
                    help="the shared store alerts are written into "
                         "('sqlite:PATH' or 'http://HOST:PORT'); omit to "
                         "evaluate+log without writing alerts")
    ap.add_argument("--token-file", default=None)
    ap.add_argument("--scrape-targets", default="",
                    help="comma list of name=http://host:port/metrics "
                         "(use 'name=self' for this process's registry)")
    ap.add_argument("--slo-config", default=None,
                    help=f"SLO objectives file (default: "
                         f"${ENV_SLO_CONFIG} or the packaged defaults)")
    ap.add_argument("--interval", type=float, default=15.0,
                    help="seconds between scrape+evaluate passes")
    ap.add_argument("--window-scale", type=float, default=1.0,
                    help="multiply every burn window (test/bench "
                         "compression; production stays 1.0)")
    ap.add_argument("--incident-dir", default=None,
                    help=f"flight-recorder bundle dir (default: "
                         f"${ENV_INCIDENT_DIR} or <trace-dir>/incidents)")
    ap.add_argument("--once", action="store_true",
                    help="one scrape+evaluate pass, print probe states, "
                         "exit 1 if anything is firing")
    ap.add_argument("--smoke", action="store_true",
                    help="the <30s verify-gate smoke: live 3-process wire "
                         "set scraped, synthetic breach must fire + clear")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.smoke:
        return smoke()
    trace.configure_from_env("monitor")
    store = None
    if args.store:
        from mpi_operator_tpu.machinery.http_store import read_token_file
        from mpi_operator_tpu.opshell.__main__ import build_store

        store = build_store(args.store,
                            token=read_token_file(args.token_file))
    try:
        monitor = build_monitor(
            store, scrape_targets=args.scrape_targets,
            slo_config=args.slo_config, interval=args.interval,
            window_scale=args.window_scale, incident_dir=args.incident_dir,
        )
    except (SLOConfigError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.once:
        states = monitor.tick()
        for name, st in sorted(states.items()):
            print(f"{name}: {'FIRING' if st.firing else 'ok'}"
                  + (f" ({st.window}, burn {st.worst_burn:.1f}x)"
                     if st.firing else ""))
        return 1 if any(s.firing for s in states.values()) else 0
    monitor.start()
    print(f"slo monitor running: {len(monitor.scraper.targets)} targets, "
          f"{len(monitor.config.objectives)} objectives, "
          f"every {args.interval:g}s", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    monitor.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
