"""HPA-style autoscaler for TPUServe: metrics window in → replica count out.

The decision core, :func:`recommend`, is a PURE function — a window of
:class:`Sample` observations, the current replica count, the (defaulted)
policy targets and a clock go in; a :class:`Decision` comes out. Every
behavior the serving SLO depends on is therefore unit-testable without a
cluster (tests/test_autoscale.py sweeps it property-style):

- **Primary signal**: desired = ceil(total_qps / target_qps_per_replica).
- **Breach escalation**: a window whose worst p99 / queue depth exceeds
  its target argues for one MORE replica than QPS alone — saturation
  shows in latency before throughput.
- **Stabilization windows** (the HPA flap suppressors): scale-up takes
  the SMALLEST recommendation over the up window (every recent sample
  must agree the load is real), scale-down the LARGEST over the down
  window (one quiet sample never sheds capacity a recent spike needed).
- **Cold-start guard**: after any scale-up, scale-down holds for
  ``cold_start_grace_s`` — fresh replicas serve nothing while warming,
  and their zero-QPS samples would otherwise immediately argue the
  scale-up back down.
- **Scale-to-zero** (min_replicas == 0 only): the window must show zero
  traffic continuously for ``scale_to_zero_after_s``. Scale FROM zero
  needs an arrival signal no pod can emit — the front door stamps the
  ``tpujob.dev/offered-qps`` annotation on the TPUServe (the KEDA-shaped
  contract) and the sampler folds it in.

The :class:`ServeAutoscaler` wrapper is the small impure shell: it samples
pod ``status.serve_stats`` through the informer, keeps the per-serve
window, and writes the verdict to ``spec.replicas`` (uid-pinned patch) —
exactly how the HPA writes a Deployment's scale subresource.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional

from mpi_operator_tpu.api.defaults import set_serve_defaults
from mpi_operator_tpu.api.types import TPUServe
from mpi_operator_tpu.controller.serve import (
    LABEL_SERVE_NAME,
    group_replicas,
    replica_ready,
)
from mpi_operator_tpu.machinery import trace
from mpi_operator_tpu.machinery.cache import InformerCache
from mpi_operator_tpu.machinery.events import NORMAL, EventRecorder
from mpi_operator_tpu.machinery.objects import PodPhase
from mpi_operator_tpu.machinery.store import Conflict, NotFound, ObjectStore
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.autoscaler")

# the scale-from-zero arrival hint (stamped by the ingress/front door;
# nothing inside the cluster can observe offered load with zero replicas)
ANNOTATION_OFFERED_QPS = "tpujob.dev/offered-qps"

EVENT_SCALE_UP = "ScaleUp"
EVENT_SCALE_DOWN = "ScaleDown"


@dataclass(frozen=True)
class Sample:
    """One observation of a serve's load (aggregated across its pods)."""

    t: float
    qps: float          # total offered/served QPS
    queue_depth: float  # worst per-pod queue depth
    p99_ms: float       # worst per-pod p99 latency
    ready: int          # ready replicas at sample time


@dataclass(frozen=True)
class Targets:
    """The defaulted AutoscalePolicy, flattened for the pure core."""

    min_replicas: int
    max_replicas: int
    target_qps_per_replica: float
    target_p99_ms: Optional[float] = None
    target_queue_depth: Optional[float] = None
    up_window_s: float = 0.0
    down_window_s: float = 30.0
    scale_to_zero_after_s: Optional[float] = None
    cold_start_grace_s: float = 15.0

    @staticmethod
    def from_policy(asc) -> "Targets":
        return Targets(
            min_replicas=asc.min_replicas,
            max_replicas=asc.max_replicas,
            target_qps_per_replica=asc.target_qps_per_replica,
            target_p99_ms=asc.target_p99_ms,
            target_queue_depth=asc.target_queue_depth,
            up_window_s=asc.scale_up_stabilization_s,
            down_window_s=asc.scale_down_stabilization_s,
            scale_to_zero_after_s=asc.scale_to_zero_after_s,
            cold_start_grace_s=asc.cold_start_grace_s,
        )


@dataclass(frozen=True)
class Decision:
    replicas: int
    reason: str


def _raw_desired(s: Sample, t: Targets, current: int) -> int:
    """The per-sample recommendation before any stabilization."""
    if s.qps <= 0:
        base = 0
    else:
        base = max(1, math.ceil(s.qps / t.target_qps_per_replica))
    # breach escalators: saturation argues for one more than we have,
    # even when raw QPS says the fleet is sized right
    if t.target_p99_ms is not None and s.p99_ms > t.target_p99_ms:
        base = max(base, max(current, s.ready) + 1)
    if (
        t.target_queue_depth is not None
        and s.queue_depth > t.target_queue_depth
    ):
        base = max(base, max(current, s.ready) + 1)
    return base


def recommend(
    samples: List[Sample],
    current: int,
    targets: Targets,
    now: float,
    last_scale_up_t: Optional[float] = None,
) -> Decision:
    """The pure decision: newest-sample-inclusive stabilization windows,
    cold-start guard, scale-to-zero grace, [min, max] clamping. ``samples``
    must be time-ordered (oldest first); an empty window holds."""
    # every verdict is clamped to [min, max] — HPA semantics: a serve
    # manually scaled below its floor (ctl serve scale, a hand-edited
    # spec) self-heals on the next tick instead of parking there until
    # traffic happens to argue it back up
    if current < targets.min_replicas:
        return Decision(
            min(targets.min_replicas, targets.max_replicas),
            f"raise to the min_replicas floor ({targets.min_replicas})",
        )
    if current > targets.max_replicas:
        return Decision(
            targets.max_replicas,
            f"lower to the max_replicas cap ({targets.max_replicas})",
        )
    if not samples:
        return Decision(current, "no-samples")
    latest = samples[-1]

    def window(w: float) -> List[Sample]:
        out = [s for s in samples if s.t >= now - w]
        return out or [latest]

    recs_up = [_raw_desired(s, targets, current) for s in window(
        targets.up_window_s)]
    recs_down = [_raw_desired(s, targets, current) for s in window(
        targets.down_window_s)]
    candidate_up = min(recs_up)
    candidate_down = max(recs_down)
    floor = max(0, targets.min_replicas)
    cap = targets.max_replicas

    if candidate_up > current:
        return Decision(
            min(max(candidate_up, floor), cap),
            f"scale-up: window agrees on >= {candidate_up} "
            f"(qps {latest.qps:g})",
        )

    if candidate_down >= current:
        return Decision(current, "steady")

    # --- scale-down path, guarded ---
    if (
        last_scale_up_t is not None
        and now - last_scale_up_t < targets.cold_start_grace_s
    ):
        return Decision(
            current,
            f"hold: cold-start grace ({targets.cold_start_grace_s:g}s "
            f"after scale-up)",
        )
    target = candidate_down
    if target <= 0:
        # zero only via the explicit zero-traffic grace
        zero_ok = (
            targets.min_replicas == 0
            and targets.scale_to_zero_after_s is not None
        )
        if zero_ok:
            horizon = now - targets.scale_to_zero_after_s
            covered = samples[0].t <= horizon
            quiet = all(s.qps <= 0 for s in samples if s.t >= horizon)
            zero_ok = covered and quiet
        if not zero_ok:
            target = max(1, floor)
            if target >= current:
                return Decision(current, "hold: zero-traffic grace not met")
            return Decision(
                min(target, cap),
                "scale-down to floor (zero grace pending)",
            )
        return Decision(0, "scale-to-zero: zero traffic past the grace")
    target = min(max(target, max(1, floor) if target > 0 else floor), cap)
    if target >= current:
        return Decision(current, "steady")
    return Decision(
        target,
        f"scale-down: down-window max is {candidate_down}",
    )


class _ServeState:
    __slots__ = ("window", "last_scale_up_t", "last_scale_down_t", "key")

    def __init__(self, key: str = ""):
        self.window: Deque[Sample] = deque(maxlen=512)
        self.last_scale_up_t: Optional[float] = None
        self.last_scale_down_t: Optional[float] = None
        self.key = key


class ServeAutoscaler:
    """The impure shell: sample → window → recommend → patch
    ``spec.replicas``. Runs leader-only next to the serve controller;
    ``tick()`` is public so tests and the bench can drive it with their
    own clock."""

    def __init__(
        self,
        store: ObjectStore,
        recorder: Optional[EventRecorder] = None,
        *,
        cache: Optional["InformerCache"] = None,
        namespace: Optional[str] = None,
        interval: float = 2.0,
    ):
        self.store = store
        self.cache = cache
        self.read = cache if cache is not None else store
        self.recorder = recorder or EventRecorder(
            store, component="tpuserve-autoscaler"
        )
        self.namespace = namespace
        self.interval = interval
        self._states: Dict[str, _ServeState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServeAutoscaler":
        self._thread = threading.Thread(
            target=self._run, name="tpuserve-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                log.exception("autoscaler tick failed; next tick retries")

    # ------------------------------------------------------------------

    def sample(self, serve: TPUServe, now: float) -> Sample:
        """Aggregate the serve's pod-reported serve_stats plus the
        front-door arrival hint into one observation."""
        pods = self.read.list(
            "Pod", serve.namespace, selector={LABEL_SERVE_NAME: serve.name}
        )
        live = [p for p in pods if not p.is_finished()]
        qps = 0.0
        queue_depth = 0.0
        p99 = 0.0
        for p in live:
            stats = p.status.serve_stats or {}
            if p.status.phase != PodPhase.RUNNING:
                continue
            qps += float(stats.get("qps", 0.0))
            queue_depth = max(queue_depth, float(stats.get("queue_depth",
                                                           0.0)))
            p99 = max(p99, float(stats.get("p99_ms", 0.0)))
        hint = serve.metadata.annotations.get(ANNOTATION_OFFERED_QPS)
        if hint:
            try:
                qps = max(qps, float(hint))
            except ValueError:
                pass  # a malformed hint must not break the loop
        workers = serve.spec.workers_per_replica or 1
        ready = sum(
            1 for members in group_replicas(live).values()
            if replica_ready(members, workers)
        )
        return Sample(t=now, qps=qps, queue_depth=queue_depth, p99_ms=p99,
                      ready=ready)

    def tick(self, now: Optional[float] = None) -> None:
        """One decision pass over every autoscaled serve."""
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        with trace.start_span("autoscaler.sync"):
            seen = set()
            for serve in self.read.list("TPUServe", self.namespace):
                seen.add(serve.metadata.uid)
                try:
                    self._tick_serve(serve, now)
                except (Conflict, NotFound):
                    continue  # stale read; next tick re-reads
            for uid in [u for u in self._states if u not in seen]:
                # deleted serve: drop its window AND its gauge series (a
                # per-object gauge must not export its last value forever)
                state = self._states.pop(uid)
                if state.key:
                    metrics.serve_desired_replicas.remove(serve=state.key)
        metrics.autoscaler_sync_latency.observe(time.perf_counter() - t0)

    def _tick_serve(self, stored: TPUServe, now: float) -> None:
        serve = set_serve_defaults(stored.deepcopy())
        asc = serve.spec.autoscale
        if asc is None:
            return
        state = self._states.setdefault(
            serve.metadata.uid, _ServeState(serve.metadata.key())
        )
        if len(self._states) > 4096:
            self._states.pop(next(iter(self._states)))
        state.window.append(self.sample(serve, now))
        # age out samples beyond the longest horizon anyone consults
        horizon = max(
            asc.scale_up_stabilization_s, asc.scale_down_stabilization_s,
            asc.scale_to_zero_after_s or 0.0,
        ) + 10.0
        while state.window and state.window[0].t < now - horizon:
            state.window.popleft()
        current = serve.spec.replicas
        decision = recommend(
            list(state.window), current, Targets.from_policy(asc), now,
            last_scale_up_t=state.last_scale_up_t,
        )
        metrics.serve_desired_replicas.set(
            decision.replicas,
            serve=f"{serve.namespace}/{serve.name}",
        )
        if decision.replicas == current:
            return
        direction = "up" if decision.replicas > current else "down"
        with trace.start_span(
            "autoscaler.scale",
            trace_id=serve.metadata.annotations.get(
                trace.ANNOTATION_TRACE_ID),
            attrs={
                "serve": serve.metadata.key(),
                "from": current, "to": decision.replicas,
                "reason": decision.reason,
            },
        ):
            # uid-pinned like every identity-sensitive write: a recreated
            # same-name serve must not inherit the old one's scale verdict
            self.store.patch(
                "TPUServe", serve.namespace, serve.name,
                {"spec": {"replicas": decision.replicas},
                 "metadata": {"uid": serve.metadata.uid}},
            )
        if direction == "up":
            state.last_scale_up_t = now
        else:
            state.last_scale_down_t = now
        metrics.serve_scale_events.inc(direction=direction)
        self.recorder.event(
            serve, NORMAL,
            EVENT_SCALE_UP if direction == "up" else EVENT_SCALE_DOWN,
            f"replicas {current} → {decision.replicas} ({decision.reason})",
        )
        log.info("%s: replicas %d → %d (%s)", serve.metadata.key(),
                 current, decision.replicas, decision.reason)
