"""ctypes binding for the native host-collective runtime (libtpucoll).

Python side of native/ (SURVEY.md §2.4's "native parity" deliverable). The
C library and this binding share the controller's TPUJOB_* rendezvous env
with the JAX runtime — one bootstrap contract for every language in the job.
Python↔C via ctypes per the environment's no-pybind11 constraint.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Sequence

_LIB_PATHS = (
    os.path.join(os.path.dirname(__file__), "..", "native", "build", "libtpucoll.so"),
    "libtpucoll.so",
)


def _load() -> ctypes.CDLL:
    last: Optional[Exception] = None
    for p in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(p) if os.path.sep in p else p)
            break
        except OSError as e:
            last = e
    else:
        raise RuntimeError(
            f"libtpucoll.so not found (build with `make -C native`): {last}"
        )
    lib.tpucoll_init.argtypes = [ctypes.POINTER(ctypes.c_void_p)]
    lib.tpucoll_init.restype = ctypes.c_int
    for fn in (lib.tpucoll_rank, lib.tpucoll_size):
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_int
    for fn in (lib.tpucoll_allreduce_sum_f64, lib.tpucoll_reduce_sum_f64):
        fn.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
        ]
        fn.restype = ctypes.c_int
    lib.tpucoll_broadcast_f64.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_size_t,
    ]
    lib.tpucoll_broadcast_f64.restype = ctypes.c_int
    for fn in (lib.tpucoll_allgather_f64, lib.tpucoll_reduce_scatter_sum_f64):
        fn.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_double),
        ]
        fn.restype = ctypes.c_int
    for fn in (lib.tpucoll_barrier, lib.tpucoll_finalize):
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ctypes.c_int
    return lib


class HostCollectives:
    """RAII wrapper: ``with HostCollectives() as hc: hc.allreduce([...])``."""

    def __init__(self):
        self._lib = _load()
        self._ctx = ctypes.c_void_p()
        rc = self._lib.tpucoll_init(ctypes.byref(self._ctx))
        if rc != 0:
            raise RuntimeError(f"tpucoll_init failed: {rc}")

    @property
    def rank(self) -> int:
        return self._lib.tpucoll_rank(self._ctx)

    @property
    def size(self) -> int:
        return self._lib.tpucoll_size(self._ctx)

    def _buf(self, values: Sequence[float]):
        arr = (ctypes.c_double * len(values))(*values)
        return arr

    def allreduce_sum(self, values: Sequence[float]) -> list:
        arr = self._buf(values)
        rc = self._lib.tpucoll_allreduce_sum_f64(self._ctx, arr, len(values))
        if rc != 0:
            raise RuntimeError(f"allreduce failed: {rc}")
        return list(arr)

    def reduce_sum(self, values: Sequence[float]) -> list:
        """Result is meaningful on host 0 only (others get their input back)."""
        arr = self._buf(values)
        rc = self._lib.tpucoll_reduce_sum_f64(self._ctx, arr, len(values))
        if rc != 0:
            raise RuntimeError(f"reduce failed: {rc}")
        return list(arr)

    def broadcast(self, values: Sequence[float]) -> list:
        """Host 0's values win everywhere (≙ hvd.broadcast_parameters)."""
        arr = self._buf(values)
        rc = self._lib.tpucoll_broadcast_f64(self._ctx, arr, len(values))
        if rc != 0:
            raise RuntimeError(f"broadcast failed: {rc}")
        return list(arr)

    def allgather(self, values: Sequence[float]) -> list:
        """Rank-ordered concatenation of every host's values (uniform length
        per host, ≙ MPI_Allgather)."""
        arr = self._buf(values)
        out = (ctypes.c_double * (len(values) * self.size))()
        rc = self._lib.tpucoll_allgather_f64(self._ctx, arr, len(values), out)
        if rc != 0:
            raise RuntimeError(f"allgather failed: {rc}")
        return list(out)

    def reduce_scatter_sum(self, values: Sequence[float]) -> list:
        """Elementwise sum scattered by rank: this host gets chunk ``rank``
        of the summed vector (len(values) must be a multiple of the gang
        size; ≙ MPI_Reduce_scatter_block — the sharded-gradient verb)."""
        if len(values) % max(1, self.size) != 0:
            raise ValueError(
                f"reduce_scatter length {len(values)} not divisible by "
                f"gang size {self.size}"
            )
        arr = self._buf(values)
        out = (ctypes.c_double * (len(values) // max(1, self.size)))()
        rc = self._lib.tpucoll_reduce_scatter_sum_f64(
            self._ctx, arr, len(values), out
        )
        if rc != 0:
            raise RuntimeError(f"reduce_scatter failed: {rc}")
        return list(out)

    def barrier(self) -> None:
        rc = self._lib.tpucoll_barrier(self._ctx)
        if rc != 0:
            raise RuntimeError(f"barrier failed: {rc}")

    def close(self) -> None:
        if self._ctx:
            self._lib.tpucoll_finalize(self._ctx)
            self._ctx = ctypes.c_void_p()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
