"""Job condition state machine.

≙ /root/reference/v2/pkg/controller/mpi_job_controller_status.go:
  updateMPIJobConditions (:49), newCondition (:62), getCondition (:73),
  isFinished/isSucceeded/isFailed/isEvicted (:85-106), setCondition (:111),
  filterOutCondition (:131-153).

Semantics preserved exactly:
- Setting a condition with the same (type, status, reason) as the current one
  is a no-op (no timestamp churn).
- Same (type, status) but new reason/message keeps last_transition_time.
- Setting Running removes any Restarting condition and vice versa.
- Setting Succeeded/Failed flips an existing Running condition to status=False
  (the job keeps a record that it *was* running).
"""

from __future__ import annotations

import time
from typing import List, Optional

from mpi_operator_tpu.api.types import Condition, ConditionType, JobStatus

# Reason strings, ≙ the constants used across the reference controller
# (mpi_job_controller.go: mpiJobCreatedReason etc. and status.go usage).
REASON_CREATED = "TPUJobCreated"
REASON_RUNNING = "TPUJobRunning"
REASON_RESTARTING = "TPUJobRestarting"
REASON_MIGRATING = "TPUJobMigrating"
REASON_SUSPENDED = "TPUJobSuspended"
REASON_RESUMED = "TPUJobResumed"
REASON_SUCCEEDED = "TPUJobSucceeded"
REASON_FAILED = "TPUJobFailed"
REASON_EVICTED = "TPUJobEvicted"
REASON_BACKOFF = "TPUJobBackoffLimitExceeded"
REASON_DEADLINE = "TPUJobDeadlineExceeded"
# the workload telemetry plane's auxiliary Straggler condition (ISSUE 15)
REASON_STRAGGLER = "StragglerDetected"
REASON_STRAGGLER_CLEARED = "StragglerCleared"


def get_condition(status: JobStatus, ctype: str) -> Optional[Condition]:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def _filter_out(conditions: List[Condition], ctype: str) -> List[Condition]:
    """≙ filterOutCondition (status.go:131-153)."""
    out: List[Condition] = []
    # Migrating is the planned-disruption flavor of Restarting: the two
    # restart-ish states and Running are mutually exclusive, exactly the
    # Running↔Restarting rule the reference pins (status.go:131-153)
    _restartish = (ConditionType.RESTARTING, ConditionType.MIGRATING)
    for c in conditions:
        if c.type == ctype:
            continue
        if ctype in _restartish and c.type == ConditionType.RUNNING:
            continue
        if ctype == ConditionType.RUNNING and c.type in _restartish:
            continue
        if ctype == ConditionType.RESTARTING and c.type == ConditionType.MIGRATING:
            continue
        if ctype == ConditionType.MIGRATING and c.type == ConditionType.RESTARTING:
            continue
        if ctype in (
            ConditionType.RESTARTING,
            ConditionType.MIGRATING,
            ConditionType.RUNNING,
        ) and c.type in (
            ConditionType.FAILED,
            ConditionType.SUCCEEDED,
        ):
            # a job that is (re)starting is no longer terminal: keep the
            # Failed/Succeeded record but flip it inactive so is_finished()
            # turns false again while the retry runs
            c.status = False
        if ctype in (ConditionType.SUCCEEDED, ConditionType.FAILED) and c.type in (
            ConditionType.RUNNING,
            ConditionType.RESTARTING,
            ConditionType.MIGRATING,
            ConditionType.SUCCEEDED,
            ConditionType.FAILED,
        ):
            # terminal condition supersedes Running, the restart-ish states
            # and any *prior* opposite terminal state (a restarted-then-
            # succeeded job must not keep reporting Failed=True — nor keep
            # an active Restarting/Migrating when the relaunched gang went
            # straight to terminal), ≙ status.go:146
            c.status = False
        out.append(c)
    return out


def set_condition(status: JobStatus, cond: Condition) -> bool:
    """≙ setCondition (status.go:111-128). Returns True if status changed."""
    current = get_condition(status, cond.type)
    if (
        current is not None
        and current.status == cond.status
        and current.reason == cond.reason
    ):
        return False
    if current is not None and current.status == cond.status:
        cond.last_transition_time = current.last_transition_time
    status.conditions = _filter_out(status.conditions, cond.type) + [cond]
    return True


def update_job_conditions(
    status: JobStatus, ctype: str, reason: str, message: str, active: bool = True
) -> bool:
    """≙ updateMPIJobConditions (status.go:49-59)."""
    return set_condition(status, Condition.new(ctype, active, reason, message))


def has_condition(status: JobStatus, ctype: str) -> bool:
    c = get_condition(status, ctype)
    return c is not None and c.status


def is_created(status: JobStatus) -> bool:
    return has_condition(status, ConditionType.CREATED)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, ConditionType.RUNNING)


def is_suspended(status: JobStatus) -> bool:
    return has_condition(status, ConditionType.SUSPENDED)


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, ConditionType.SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, ConditionType.FAILED)


def is_finished(status: JobStatus) -> bool:
    """≙ isFinished (status.go:85-87)."""
    return is_succeeded(status) or is_failed(status)


def is_evicted(status: JobStatus) -> bool:
    """≙ isEvicted (status.go:99-106): failed with the eviction reason."""
    c = get_condition(status, ConditionType.FAILED)
    return c is not None and c.status and c.reason == REASON_EVICTED


def ensure_timestamps(status: JobStatus) -> None:
    """Set start/completion timestamps from condition flips (the reference sets
    StartTime at Created, syncHandler :532-543, and CompletionTime on
    terminal conditions, updateMPIJobStatus :921-996). A restart un-finishes
    the job, so a stale completion_time is dropped until it finishes again."""
    now = time.time()
    if status.start_time is None and is_created(status):
        status.start_time = now
    if is_finished(status):
        if status.completion_time is None:
            status.completion_time = now
    else:
        status.completion_time = None
