"""Regenerate deploy/tpujob-schema.json from the API dataclasses.

≙ hack/update-codegen.sh + hack/python-sdk/gen-sdk.sh in the reference
(generate artifacts from the Go types); here the schema derives from the
dataclasses, so this is the whole generator.

  python -m mpi_operator_tpu.api.gen_schema [out-path]
"""

from __future__ import annotations

import json
import os
import sys

from mpi_operator_tpu.api.schema import json_schema

DEFAULT_OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "deploy",
    "tpujob-schema.json",
)


def main(argv=None) -> int:
    out = (argv or sys.argv[1:] or [DEFAULT_OUT])[0]
    with open(out, "w") as f:
        json.dump(json_schema(), f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
