"""TPUJobClient: the typed SDK surface.

≙ the reference's generated Python SDK (/root/reference/sdk/python/mpijob/:
``V1MPIJob`` models + a kubernetes client, used by
sdk/python/examples/tensorflow-mnist.py to submit a job programmatically).
Here the dataclasses ARE the models, so the client is a thin typed facade
over any store backend (in-process ObjectStore or the shared SqliteStore):

    client = TPUJobClient(store)
    job = client.create({...manifest dict...})     # strict-parsed
    client.wait(job.name, until=is_succeeded)
    client.delete(job.name)

``create`` accepts a TPUJob or a manifest dict; dicts go through the strict
structural schema (api/schema.py) — unknown fields fail loudly, exactly the
apiserver-CRD behavior the reference relies on — and are admission-validated
(defaulted copy) so bad specs are rejected at submit time, not at reconcile.
"""

from __future__ import annotations

import queue
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

from mpi_operator_tpu.api.defaults import set_defaults, set_serve_defaults
from mpi_operator_tpu.api.schema import (
    ManifestError,
    parse_tpujob,
    parse_tpuserve,
)
from mpi_operator_tpu.api.types import TPUJob, TPUServe
from mpi_operator_tpu.api.validation import validate_tpujob, validate_tpuserve


class ValidationRejected(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("TPUJob rejected:\n  " + "\n  ".join(errors))


class TPUJobClient:
    """Typed create/get/list/watch/delete for TPUJobs over a store."""

    KIND = "TPUJob"

    def __init__(self, store, namespace: str = "default"):
        self.store = store
        self.namespace = namespace

    # -- admission ----------------------------------------------------------

    @staticmethod
    def load(manifest: Union[TPUJob, Dict[str, Any]]) -> TPUJob:
        """dict → TPUJob through the strict schema; TPUJob passes through."""
        if isinstance(manifest, TPUJob):
            return manifest
        return parse_tpujob(manifest)

    def create(self, manifest: Union[TPUJob, Dict[str, Any]]) -> TPUJob:
        from mpi_operator_tpu.machinery import trace

        job = self.load(manifest).deepcopy()
        if not job.metadata.namespace or job.metadata.namespace == "default":
            job.metadata.namespace = self.namespace
        # trace anchor, stamped at ADMISSION (machinery/trace.py): every
        # span any component ever opens for this job's lifecycle groups
        # under this id — `ctl trace <job>` starts here. setdefault, so a
        # caller-provided id (a CI pipeline threading its own trace
        # through) is honored.
        job.metadata.annotations.setdefault(
            trace.ANNOTATION_TRACE_ID, trace.new_trace_id()
        )
        # admission: validate a defaulted copy (the controller re-defaults at
        # reconcile; stored spec stays exactly what the user wrote)
        errors = validate_tpujob(set_defaults(job.deepcopy()))
        if errors:
            raise ValidationRejected(errors)
        with trace.start_span(
            "client.submit",
            trace_id=job.metadata.annotations[trace.ANNOTATION_TRACE_ID],
            attrs={"job": f"{job.metadata.namespace}/{job.metadata.name}"},
        ):
            return self.store.create(job)

    def update(self, job: TPUJob) -> TPUJob:
        """Admission-validated spec update (scale, suspend, …): the same
        defaulted-copy validation as ``create``, then an optimistic store
        update (Conflict propagates; re-get and retry)."""
        errors = validate_tpujob(set_defaults(job.deepcopy()))
        if errors:
            raise ValidationRejected(errors)
        return self.store.update(job)

    # -- read ---------------------------------------------------------------

    def get(self, name: str, namespace: Optional[str] = None) -> TPUJob:
        return self.store.get(self.KIND, namespace or self.namespace, name)

    def list(self, namespace: Optional[str] = None) -> List[TPUJob]:
        return self.store.list(self.KIND, namespace or self.namespace)

    def delete(self, name: str, namespace: Optional[str] = None) -> TPUJob:
        return self.store.delete(self.KIND, namespace or self.namespace, name)

    # -- watch / wait -------------------------------------------------------

    def watch(self, timeout: Optional[float] = None) -> Iterator[TPUJob]:
        """Yield job objects as they change (ADDED/MODIFIED), until timeout
        (None = forever; the caller breaks out)."""
        q = self.store.watch(self.KIND)
        deadline = None if timeout is None else time.time() + timeout
        try:
            while True:
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return
                try:
                    ev = q.get(timeout=remaining if remaining is not None else 1.0)
                except queue.Empty:
                    if deadline is None:
                        continue
                    return
                if ev.type in ("ADDED", "MODIFIED"):
                    yield ev.obj
        finally:
            self.store.stop_watch(q)

    def wait(
        self,
        name: str,
        *,
        until: Callable[[Any], bool],
        timeout: float = 300.0,
        namespace: Optional[str] = None,
    ) -> TPUJob:
        """Block until ``until(job.status)`` holds; raises TimeoutError
        (NotFound if the job is deleted mid-wait).

        Watch-based on every backend (≙ kubectl wait riding the watch API):
        the store's watch queue delivers changes — long-poll over HTTP,
        poll-free in-process — instead of a get round-trip per tick. The
        watch registers BEFORE the initial read so no transition between
        them is lost; relist recovery re-delivers as MODIFIED, which a
        level-triggered predicate absorbs."""
        from mpi_operator_tpu.machinery.store import DELETED, NotFound

        ns = namespace or self.namespace
        q = self.store.watch(self.KIND)
        try:
            job = self.store.get(self.KIND, ns, name)
            if until(job.status):
                return job
            deadline = time.time() + timeout
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(
                        f"TPUJob {ns}/{name} did not reach the desired state"
                    )
                try:
                    ev = q.get(timeout=min(remaining, 1.0))
                except queue.Empty:
                    # idle resync (≙ the informer's periodic relist): relist
                    # recovery after a watch gap only re-delivers LIVE
                    # objects, so a deletion that fell inside the gap would
                    # otherwise never surface. One level-triggered read per
                    # idle second bounds that — NotFound propagates.
                    job = self.store.get(self.KIND, ns, name)
                    if until(job.status):
                        return job
                    continue
                m = ev.obj.metadata
                if m.name != name or m.namespace != ns:
                    continue
                if ev.type == DELETED:
                    raise NotFound(f"TPUJob {ns}/{name} deleted while waiting")
                # oplint: disable=LEV001 — a wait-until helper is an
                # OBSERVER, not a reconciler: "the predicate held in some
                # observed state" is exactly wait semantics (kube's
                # wait.UntilWithSync does the same), and the idle-resync
                # branch above already re-reads live state whenever the
                # watch goes quiet, so a dropped edge cannot strand us
                if until(ev.obj.status):
                    return ev.obj
        finally:
            self.store.stop_watch(q)


class TPUServeClient:
    """Typed create/get/list/delete for the serving workload class — the
    TPUJobClient's twin over kind TPUServe, with the same admission
    posture: strict schema on dict manifests, validation on a DEFAULTED
    copy (the stored spec stays what the user wrote), and the trace-id
    anchor stamped at admission so `ctl trace <serve>` has a timeline."""

    KIND = "TPUServe"

    def __init__(self, store, namespace: str = "default"):
        self.store = store
        self.namespace = namespace

    @staticmethod
    def load(manifest: Union[TPUServe, Dict[str, Any]]) -> TPUServe:
        if isinstance(manifest, TPUServe):
            return manifest
        return parse_tpuserve(manifest)

    def create(self, manifest: Union[TPUServe, Dict[str, Any]]) -> TPUServe:
        from mpi_operator_tpu.machinery import trace

        serve = self.load(manifest).deepcopy()
        if not serve.metadata.namespace or serve.metadata.namespace == "default":
            serve.metadata.namespace = self.namespace
        serve.metadata.annotations.setdefault(
            trace.ANNOTATION_TRACE_ID, trace.new_trace_id()
        )
        errors = validate_tpuserve(set_serve_defaults(serve.deepcopy()))
        if errors:
            raise ValidationRejected(errors)
        with trace.start_span(
            "client.submit",
            trace_id=serve.metadata.annotations[trace.ANNOTATION_TRACE_ID],
            attrs={"serve": f"{serve.metadata.namespace}/{serve.metadata.name}"},
        ):
            return self.store.create(serve)

    def update(self, serve: TPUServe) -> TPUServe:
        errors = validate_tpuserve(set_serve_defaults(serve.deepcopy()))
        if errors:
            raise ValidationRejected(errors)
        return self.store.update(serve)

    def get(self, name: str, namespace: Optional[str] = None) -> TPUServe:
        return self.store.get(self.KIND, namespace or self.namespace, name)

    def list(self, namespace: Optional[str] = None) -> List[TPUServe]:
        return self.store.list(self.KIND, namespace or self.namespace)

    def delete(self, name: str, namespace: Optional[str] = None) -> TPUServe:
        return self.store.delete(self.KIND, namespace or self.namespace, name)

    def wait(
        self,
        name: str,
        *,
        until: Callable[[Any], bool],
        timeout: float = 300.0,
        namespace: Optional[str] = None,
        poll: float = 0.1,
    ) -> TPUServe:
        """Block until ``until(serve)`` holds (NOTE: predicate over the
        whole object, not just status — rollout predicates need spec and
        status together). Level-polled: serve state changes ride bursts
        of pod/status churn, so a simple bounded poll stays simpler than
        a watch here and is test/bench-facing only."""
        ns = namespace or self.namespace
        deadline = time.time() + timeout
        while True:
            serve = self.store.get(self.KIND, ns, name)
            if until(serve):
                return serve
            if time.time() >= deadline:
                raise TimeoutError(
                    f"TPUServe {ns}/{name} did not reach the desired state"
                )
            time.sleep(poll)


__all__ = [
    "TPUJobClient", "TPUServeClient", "ValidationRejected", "ManifestError",
]
