"""Job API layer: types, defaulting, validation, condition state machine.

Capability parity with the reference API packages:
- types:      /root/reference/v2/pkg/apis/kubeflow/v2beta1/types.go
- defaults:   /root/reference/v2/pkg/apis/kubeflow/v2beta1/default.go
- validation: /root/reference/v2/pkg/apis/kubeflow/validation/validation.go
- conditions: /root/reference/v2/pkg/controller/mpi_job_controller_status.go
"""

from mpi_operator_tpu.api.types import (  # noqa: F401
    CleanPodPolicy,
    Condition,
    ConditionType,
    Container,
    ElasticPolicy,
    JobStatus,
    ObjectMeta,
    PodTemplate,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SliceSpec,
    TPUJob,
    TPUJobSpec,
)
from mpi_operator_tpu.api.defaults import set_defaults  # noqa: F401
from mpi_operator_tpu.api.validation import ValidationError, validate_tpujob  # noqa: F401
from mpi_operator_tpu.api import conditions  # noqa: F401
from mpi_operator_tpu.api.schema import (  # noqa: F401
    ManifestError,
    check_manifest,
    json_schema,
    parse_tpujob,
)
from mpi_operator_tpu.api.client import TPUJobClient, ValidationRejected  # noqa: F401
