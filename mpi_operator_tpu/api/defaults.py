"""Defaulting for TPUJob.

≙ the registered scheme defaulters the reference controller applies at the top
of every reconcile (scheme.Scheme.Default(mpiJob), v2/pkg/controller/
mpi_job_controller.go:475; defaults defined in
/root/reference/v2/pkg/apis/kubeflow/v2beta1/default.go:52-71):

reference defaults                       → TPU-native defaults
-----------------------------------------------------------------------------
CleanPodPolicy = None                    → same
SlotsPerWorker = 1                       → same (chips per host)
SSHAuthMountPath = /root/.ssh            → (no SSH on TPU; dropped)
MPIImplementation = OpenMPI              → slice.accelerator = "cpu" test backend
launcher replicas = 1, worker = 0        → worker replicas = 1 (launcher-less;
                                           a 0-worker SPMD job is meaningless)
RestartPolicy (common default Never)     → same
"""

from __future__ import annotations

from mpi_operator_tpu.api.types import (
    CleanPodPolicy,
    RestartPolicy,
    TPUJob,
    TPUServe,
    family_chips_per_host,
)

DEFAULT_SLOTS_PER_WORKER = 1
DEFAULT_WORKER_REPLICAS = 1
DEFAULT_RESTART_POLICY = RestartPolicy.NEVER
DEFAULT_ACCELERATOR = "cpu"
# the persistent compile cache defaults ON (ISSUE 16): restart paths are
# exactly where the operator spends its cleverness, and a warm cache is
# what makes them cheap; the spec knob exists to opt OUT
DEFAULT_COMPILE_CACHE = True

# TPUServe defaults: serving outranks batch by default (the workload-class
# distinction — see TPUServeSpec), one-host gangs, a Deployment-shaped
# (surge 1 / unavailable 0) zero-unready-window rollout, and conservative
# HPA stabilization (instant up, 30s down, 15s cold-start hold).
DEFAULT_SERVE_REPLICAS = 1
DEFAULT_SERVE_WORKERS = 1
DEFAULT_SERVE_PRIORITY = "high"
DEFAULT_SERVE_MAX_SURGE = 1
DEFAULT_SERVE_MAX_UNAVAILABLE = 0
DEFAULT_AUTOSCALE_MIN = 1
DEFAULT_AUTOSCALE_MAX = 8
DEFAULT_TARGET_QPS_PER_REPLICA = 100.0
DEFAULT_SCALE_UP_STABILIZATION_S = 0.0
DEFAULT_SCALE_DOWN_STABILIZATION_S = 30.0
DEFAULT_COLD_START_GRACE_S = 15.0


def set_defaults(job: TPUJob) -> TPUJob:
    """Mutates ``job`` in place, filling unset fields; returns it for chaining.

    Idempotent, like the reference's defaulters (default_test.go asserts
    set-fields are preserved; see tests/test_api_defaults.py).
    """
    spec = job.spec
    if not spec.slice.accelerator:
        spec.slice.accelerator = DEFAULT_ACCELERATOR
    if spec.slots_per_worker is None:
        # TPU families have a hardware-fixed chips-per-host (4 for v4..v6e);
        # defaulting slots to it keeps the derived topology coherent. The cpu
        # test family keeps the reference default of 1 (default.go:52-71).
        spec.slots_per_worker = (
            family_chips_per_host(spec.slice.accelerator) or DEFAULT_SLOTS_PER_WORKER
        )
    if spec.run_policy.clean_pod_policy is None:
        spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
    if spec.worker.replicas is None:
        spec.worker.replicas = DEFAULT_WORKER_REPLICAS
    if spec.worker.restart_policy is None:
        spec.worker.restart_policy = DEFAULT_RESTART_POLICY
    # slots_per_worker is the user knob; chips_per_host follows it only when
    # genuinely unset (None), so an explicit chips_per_host=1 is preserved.
    if spec.slice.chips_per_host is None:
        spec.slice.chips_per_host = spec.slots_per_worker
    if spec.elastic is not None:
        if spec.elastic.min_replicas is None:
            spec.elastic.min_replicas = 1
        if spec.elastic.max_replicas is None:
            spec.elastic.max_replicas = spec.worker.replicas
    if spec.compile_cache is None:
        spec.compile_cache = DEFAULT_COMPILE_CACHE
    return job


def set_serve_defaults(serve: TPUServe) -> TPUServe:
    """Idempotent in-place defaulting for TPUServe (same contract as
    ``set_defaults``: the controller re-defaults every reconcile; stored
    specs stay exactly what the user wrote)."""
    spec = serve.spec
    if not spec.slice.accelerator:
        spec.slice.accelerator = DEFAULT_ACCELERATOR
    if spec.slice.chips_per_host is None:
        spec.slice.chips_per_host = (
            family_chips_per_host(spec.slice.accelerator)
            or DEFAULT_SLOTS_PER_WORKER
        )
    if spec.workers_per_replica is None:
        spec.workers_per_replica = DEFAULT_SERVE_WORKERS
    if spec.priority_class is None:
        spec.priority_class = DEFAULT_SERVE_PRIORITY
    if spec.max_surge is None:
        spec.max_surge = DEFAULT_SERVE_MAX_SURGE
    if spec.max_unavailable is None:
        spec.max_unavailable = DEFAULT_SERVE_MAX_UNAVAILABLE
    asc = spec.autoscale
    if asc is not None:
        if asc.min_replicas is None:
            asc.min_replicas = DEFAULT_AUTOSCALE_MIN
        if asc.max_replicas is None:
            asc.max_replicas = max(DEFAULT_AUTOSCALE_MAX,
                                   asc.min_replicas,
                                   spec.replicas or 0)
        if asc.target_qps_per_replica is None:
            asc.target_qps_per_replica = DEFAULT_TARGET_QPS_PER_REPLICA
        if asc.scale_up_stabilization_s is None:
            asc.scale_up_stabilization_s = DEFAULT_SCALE_UP_STABILIZATION_S
        if asc.scale_down_stabilization_s is None:
            asc.scale_down_stabilization_s = (
                DEFAULT_SCALE_DOWN_STABILIZATION_S
            )
        if asc.cold_start_grace_s is None:
            asc.cold_start_grace_s = DEFAULT_COLD_START_GRACE_S
    if spec.replicas is None:
        # an autoscaled serve starts at its floor (never below 1 — the
        # scale-to-zero decision belongs to the autoscaler's zero-traffic
        # window, not to defaulting)
        spec.replicas = (
            max(1, asc.min_replicas) if asc is not None
            else DEFAULT_SERVE_REPLICAS
        )
    return serve


def effective_disruption_budget(serve: TPUServe) -> int:
    """THE DisruptionBudget rule (ISSUE 14), shared by the serve
    controller's retire gate and the DrainController's blocked-drain
    reporting so the two can never disagree: an unset budget defaults to
    ``replicas - max_unavailable`` (planned disruption is never allowed
    to be worse than a rollout). Callers max() this with the rollout
    floor — an explicit low value relaxes toward that floor, never below
    it. Call on a DEFAULTED serve (after :func:`set_serve_defaults`)."""
    spec = serve.spec
    if spec.disruption_budget is not None:
        return max(0, spec.disruption_budget)
    return max(0, (spec.replicas or 0) - (spec.max_unavailable or 0))
