"""Defaulting for TPUJob.

≙ the registered scheme defaulters the reference controller applies at the top
of every reconcile (scheme.Scheme.Default(mpiJob), v2/pkg/controller/
mpi_job_controller.go:475; defaults defined in
/root/reference/v2/pkg/apis/kubeflow/v2beta1/default.go:52-71):

reference defaults                       → TPU-native defaults
-----------------------------------------------------------------------------
CleanPodPolicy = None                    → same
SlotsPerWorker = 1                       → same (chips per host)
SSHAuthMountPath = /root/.ssh            → (no SSH on TPU; dropped)
MPIImplementation = OpenMPI              → slice.accelerator = "cpu" test backend
launcher replicas = 1, worker = 0        → worker replicas = 1 (launcher-less;
                                           a 0-worker SPMD job is meaningless)
RestartPolicy (common default Never)     → same
"""

from __future__ import annotations

from mpi_operator_tpu.api.types import (
    CleanPodPolicy,
    RestartPolicy,
    TPUJob,
    family_chips_per_host,
)

DEFAULT_SLOTS_PER_WORKER = 1
DEFAULT_WORKER_REPLICAS = 1
DEFAULT_RESTART_POLICY = RestartPolicy.NEVER
DEFAULT_ACCELERATOR = "cpu"


def set_defaults(job: TPUJob) -> TPUJob:
    """Mutates ``job`` in place, filling unset fields; returns it for chaining.

    Idempotent, like the reference's defaulters (default_test.go asserts
    set-fields are preserved; see tests/test_api_defaults.py).
    """
    spec = job.spec
    if not spec.slice.accelerator:
        spec.slice.accelerator = DEFAULT_ACCELERATOR
    if spec.slots_per_worker is None:
        # TPU families have a hardware-fixed chips-per-host (4 for v4..v6e);
        # defaulting slots to it keeps the derived topology coherent. The cpu
        # test family keeps the reference default of 1 (default.go:52-71).
        spec.slots_per_worker = (
            family_chips_per_host(spec.slice.accelerator) or DEFAULT_SLOTS_PER_WORKER
        )
    if spec.run_policy.clean_pod_policy is None:
        spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
    if spec.worker.replicas is None:
        spec.worker.replicas = DEFAULT_WORKER_REPLICAS
    if spec.worker.restart_policy is None:
        spec.worker.restart_policy = DEFAULT_RESTART_POLICY
    # slots_per_worker is the user knob; chips_per_host follows it only when
    # genuinely unset (None), so an explicit chips_per_host=1 is preserved.
    if spec.slice.chips_per_host is None:
        spec.slice.chips_per_host = spec.slots_per_worker
    if spec.elastic is not None:
        if spec.elastic.min_replicas is None:
            spec.elastic.min_replicas = 1
        if spec.elastic.max_replicas is None:
            spec.elastic.max_replicas = spec.worker.replicas
    return job
