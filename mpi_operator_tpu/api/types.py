"""TPUJob API types.

Capability parity with the reference MPIJob v2beta1 API
(/root/reference/v2/pkg/apis/kubeflow/v2beta1/types.go:25-80), redesigned for TPU:

* **Launcher-less SPMD.** The reference models jobs as 1 Launcher (runs
  ``mpirun``) + N Workers (run ``sshd``) because MPI spawns ranks from a single
  point (types.go:59-67 ``MPIReplicaSpecs{Launcher,Worker}``). On TPU every
  host boots the *same* program and rendezvouses with a coordinator
  (``jax.distributed.initialize``), so ``TPUJobSpec`` has only a Worker replica
  spec; worker 0 doubles as the coordinator. Status semantics the reference
  derives from the launcher pod (Succeeded/Failed mirroring) are derived from
  worker 0 here — the mapping is documented on ``ReplicaType``.
* **slotsPerWorker → chips per host.** The reference's ``SlotsPerWorker``
  (types.go:44-47) counts MPI slots per pod; here it is the number of TPU
  chips attached to each host, which together with ``SliceSpec`` determines
  the global device mesh.
* **MPIImplementation (OpenMPI/Intel, types.go:74-79) has no TPU analogue** —
  the collective fabric is XLA over ICI/DCN; instead ``SliceSpec`` captures
  the slice topology the mesh is built from.

Everything is a plain dataclass with ``to_dict``/``from_dict`` so job specs can
round-trip through YAML/JSON manifests (≙ the CRD structural schema,
/root/reference/manifests/base/crd.yaml:15-197) and the Python SDK
(≙ /root/reference/sdk/python/mpijob/models/).
"""

from __future__ import annotations

import copy
import dataclasses
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

API_VERSION = "tpujob.dev/v1"
KIND_TPUJOB = "TPUJob"
KIND_TPUSERVE = "TPUServe"
KIND_ALERT = "Alert"

# Per-family host geometry: the block of the chip mesh owned by one host.
# This is physical knowledge the whole stack shares (defaulting, validation,
# placement, mesh construction): v4/v5p hosts own a 2x2x1 block of the 3-D
# torus (4 chips); v5e/v6e hosts own 2x2 of the 2-D mesh (4 chips); the "cpu"
# test family is 1-D with a free chips-per-host (emulated device count).
HOST_BLOCK: Dict[str, tuple] = {
    "v4": (2, 2, 1),
    "v5p": (2, 2, 1),
    "v5e": (2, 2),
    "v6e": (2, 2),
    "cpu": (1,),
}


def family_chips_per_host(accelerator: str) -> Optional[int]:
    """Chips per host fixed by the hardware family; None for unknown families
    and for "cpu" (emulated hosts hold any number of devices)."""
    if accelerator == "cpu":
        return None
    block = HOST_BLOCK.get(accelerator)
    if block is None:
        return None
    n = 1
    for b in block:
        n *= b
    return n


def host_block_for(accelerator: str, chips_per_host: Optional[int]) -> Optional[tuple]:
    """The chip-mesh block one host owns, for a given chips-per-host request.
    Returns None when the combination is physically illegal. This is the ONE
    place sub-host geometry is defined — validation (admission), placement
    (scheduling) and mesh construction (runtime) all consult it, so they can
    never disagree.

    Sub-host slices (chips_per_host < family chips) exist only as single-host
    configurations (e.g. v5e-1 = 1x1, v5e-2 = 2x1); legal values are 1, 2, or
    the full block."""
    if accelerator == "cpu":
        return (max(1, chips_per_host or 1),)
    fam = HOST_BLOCK.get(accelerator)
    if fam is None:
        return None
    full = family_chips_per_host(accelerator)
    cph = chips_per_host or full
    if cph == full:
        return fam
    if cph == 1:
        return tuple(1 for _ in fam)
    if cph == 2:
        return (2,) + tuple(1 for _ in fam[1:])
    return None


def compute_host_mesh(topology: tuple, block: tuple) -> Optional[tuple]:
    """Host mesh = chip topology / per-host block, dimension-wise. None when
    the dimensionality differs or any axis is not divisible — the shared
    shape check behind both admission validation and gang placement."""
    if len(topology) != len(block):
        return None
    mesh = []
    for t, b in zip(topology, block):
        if b <= 0 or t % b != 0:
            return None
        mesh.append(t // b)
    return tuple(mesh)


# ---------------------------------------------------------------------------
# Enums (plain str constants: keeps YAML round-trip trivial)
# ---------------------------------------------------------------------------

class CleanPodPolicy:
    """What to do with worker pods when the job finishes.

    ≙ common.CleanPodPolicy used by MPIJobSpec.CleanPodPolicy
    (reference v2beta1/types.go:49-53; enforcement in
    v2/pkg/controller/mpi_job_controller.go:492-530).
    """

    NONE = "None"
    RUNNING = "Running"
    ALL = "All"

    ALL_VALUES = (NONE, RUNNING, ALL)


class RestartPolicy:
    """Per-replica restart policy.

    ≙ common.RestartPolicy; the reference maps EXIT_CODE to pod policy Never so
    the controller owns restart semantics
    (v2/pkg/controller/mpi_job_controller.go:1394-1400).
    """

    NEVER = "Never"
    ON_FAILURE = "OnFailure"
    ALWAYS = "Always"
    EXIT_CODE = "ExitCode"

    ALL_VALUES = (NEVER, ON_FAILURE, ALWAYS, EXIT_CODE)


class ReplicaType:
    """Replica roles.

    The reference has Launcher + Worker (v2beta1/types.go:82-90). TPU jobs are
    SPMD: every host runs the same program, so there is a single Worker type and
    **worker 0 is the coordinator** (rendezvous server + the pod whose exit
    status is mirrored into job success/failure, the role the launcher pod's
    exit status plays in updateMPIJobStatus,
    v2/pkg/controller/mpi_job_controller.go:921-996).
    """

    WORKER = "Worker"

    ALL_VALUES = (WORKER,)


class ConditionType:
    """Job condition types — same state machine as the reference
    (v2/pkg/controller/mpi_job_controller_status.go:49-153 + common.JobStatus):
    Created → Running → (Restarting ↔ Running) → Succeeded | Failed,
    plus Suspended (run policy)."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    # the planned-disruption flavor of Restarting: the gang is being
    # checkpoint-migrated off a draining node (reason names the node). A
    # Migrating restart is FREE — restart_generation advances, the
    # backoffLimit budget does not (disruption plane, ISSUE 14).
    MIGRATING = "Migrating"
    SUSPENDED = "Suspended"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    # workload-telemetry condition (ISSUE 15), AUXILIARY — it coexists
    # with Running rather than riding the exclusive restart-ish slot: a
    # gang member whose step p50 exceeds the gang median by the skew
    # threshold (controller/goodput.py) flips this active with the pod
    # and node in the reason/message; it flips inactive when the skew
    # clears or the member is replaced.
    STRAGGLER = "Straggler"

    ALL_VALUES = (CREATED, RUNNING, RESTARTING, MIGRATING, SUSPENDED,
                  SUCCEEDED, FAILED, STRAGGLER)


# ---------------------------------------------------------------------------
# Serialization helpers
# ---------------------------------------------------------------------------

def _prune(d: Any) -> Any:
    """Drop None values / empty containers recursively for compact manifests.

    Children are pruned *first* so a nested object whose members all prune away
    collapses to nothing rather than surviving as ``{}`` (which would break the
    to_dict/from_dict round-trip)."""
    if isinstance(d, dict):
        out = {}
        for k, v in d.items():
            pv = _prune(v)
            if pv is None or pv == {} or pv == []:
                continue
            out[k] = pv
        return out
    if isinstance(d, list):
        return [_prune(v) for v in d]
    return d


class _Dictable:
    def to_dict(self) -> Dict[str, Any]:
        return _prune(dataclasses.asdict(self))

    def deepcopy(self):
        return copy.deepcopy(self)


# ---------------------------------------------------------------------------
# Metadata (≙ k8s ObjectMeta, the subset the reference controller touches)
# ---------------------------------------------------------------------------

@dataclass
class OwnerReference(_Dictable):
    api_version: str = API_VERSION
    kind: str = KIND_TPUJOB
    name: str = ""
    uid: str = ""
    controller: bool = True

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "OwnerReference":
        return OwnerReference(
            api_version=d.get("api_version", API_VERSION),
            kind=d.get("kind", KIND_TPUJOB),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=d.get("controller", True),
        )


@dataclass
class ObjectMeta(_Dictable):
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: Optional[float] = None
    deletion_timestamp: Optional[float] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ObjectMeta":
        return ObjectMeta(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=d.get("resource_version", 0),
            generation=d.get("generation", 0),
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            owner_references=[OwnerReference.from_dict(o) for o in d.get("owner_references", [])],
            creation_timestamp=d.get("creation_timestamp"),
            deletion_timestamp=d.get("deletion_timestamp"),
        )

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# Pod template (the subset of corev1.PodTemplateSpec the framework schedules)
# ---------------------------------------------------------------------------

@dataclass
class Container(_Dictable):
    """Main container of a worker pod.

    ≙ the ReplicaSpec.Template containers the reference passes through to pods
    (v2/pkg/controller/mpi_job_controller.go:1246-1296 newWorker). ``resources``
    uses the TPU-native resource name ``tpu`` (≙ google.com/tpu) where the
    reference examples request nvidia.com/gpu."""

    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    working_dir: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Container":
        # env accepts both the native mapping form and the k8s list form
        # [{name: ..., value: ...}] so reference-shaped manifests port
        # mechanically (a plain dict() of the list form would silently
        # produce {"name": "value"}).
        env = d.get("env", {})
        if isinstance(env, list):
            env = {e["name"]: str(e.get("value", "")) for e in env}
        return Container(
            image=d.get("image", ""),
            command=list(d.get("command", [])),
            args=list(d.get("args", [])),
            env=dict(env),
            resources=dict(d.get("resources", {})),
            working_dir=d.get("working_dir", ""),
        )


@dataclass
class PodTemplate(_Dictable):
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    container: Container = field(default_factory=Container)
    node_selector: Dict[str, str] = field(default_factory=dict)
    scheduler_name: str = ""
    priority_class: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodTemplate":
        # Accept both the native singular form and the k8s-style plural list
        # (first entry is the main container, ≙ the v1 API's MainContainer
        # convention, reference pkg/apis/kubeflow/v1/types.go:55-62) so
        # reference-shaped manifests port mechanically.
        cont = d.get("container")
        if cont is None:
            plural = d.get("containers") or [{}]
            cont = plural[0]
        return PodTemplate(
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            container=Container.from_dict(cont),
            node_selector=dict(d.get("node_selector", {})),
            scheduler_name=d.get("scheduler_name", ""),
            priority_class=d.get("priority_class", ""),
        )


@dataclass
class ReplicaSpec(_Dictable):
    """≙ common.ReplicaSpec (replicas + template + restartPolicy) used by
    MPIReplicaSpecs (reference v2beta1/types.go:59-67)."""

    replicas: Optional[int] = None
    restart_policy: Optional[str] = None
    template: PodTemplate = field(default_factory=PodTemplate)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ReplicaSpec":
        return ReplicaSpec(
            replicas=d.get("replicas"),
            restart_policy=d.get("restart_policy"),
            template=PodTemplate.from_dict(d.get("template", {})),
        )


# ---------------------------------------------------------------------------
# TPU-specific spec pieces
# ---------------------------------------------------------------------------

@dataclass
class SliceSpec(_Dictable):
    """TPU slice request — the TPU-native replacement for the reference's
    implicit "cluster shape" (hostfile slots, v2/pkg/controller/
    mpi_job_controller.go:1088-1113).

    ``accelerator`` names the slice family (e.g. ``v5p``, ``v5e``, or ``cpu``
    for the multiprocess CPU test backend, §4 of SURVEY.md). ``topology`` is
    the per-slice ICI mesh shape (e.g. ``4x4x4``); empty means derive from
    worker count. ``chips_per_host`` is fixed per family (4 for v5p hosts);
    ``None`` means "derive from slots_per_worker" at defaulting time.
    ``num_slices > 1`` requests a multi-slice job: ``num_slices`` identical
    ICI slices joined over DCN (workers divide evenly across slices; the
    runtime builds a hybrid mesh whose DCN axes are outermost — SURVEY.md
    §5.8).
    """

    accelerator: str = "cpu"
    topology: str = ""
    chips_per_host: Optional[int] = None
    num_slices: int = 1

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SliceSpec":
        return SliceSpec(
            accelerator=d.get("accelerator", "cpu"),
            topology=d.get("topology", ""),
            chips_per_host=d.get("chips_per_host"),
            num_slices=d.get("num_slices", 1),
        )


@dataclass
class ElasticPolicy(_Dictable):
    """Elastic worker membership bounds.

    ≙ horovodrun ``-np/--min-np/--max-np`` driven by the controller-published
    discover_hosts.sh (reference examples/horovod/tensorflow-mnist-elastic.yaml:20-27,
    v2/pkg/controller/mpi_job_controller.go:1116-1138)."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ElasticPolicy":
        return ElasticPolicy(
            min_replicas=d.get("min_replicas"), max_replicas=d.get("max_replicas")
        )


@dataclass
class SchedulingPolicy(_Dictable):
    """Gang-scheduling knobs. ≙ common.SchedulingPolicy consumed by newPodGroup
    (reference v2/pkg/controller/mpi_job_controller.go:1215-1237).

    ``priority_class`` orders pending gangs in the scheduler: a built-in
    class name (low | default | high | critical) or a bare integer string
    (higher admits first; default 0). Unlike the reference — which stamps
    the field onto a Volcano PodGroup and hopes an external scheduler
    honors it — admission here implements the ordering itself
    (scheduler/gang.py), with an aging guard so a starved low-priority
    gang eventually reaches the head. The reference's ``queue`` field
    (a Volcano capacity-pool name) is deliberately NOT carried: this
    framework's capacity model is the slice inventory / node capacities,
    and a declared-but-unenforced knob would be exactly the silent-config
    pattern this API refuses elsewhere (cf. RunPolicy, implemented here
    though declared-only in the reference)."""

    min_available: Optional[int] = None
    priority_class: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SchedulingPolicy":
        return SchedulingPolicy(
            min_available=d.get("min_available"),
            priority_class=d.get("priority_class", ""),
        )


@dataclass
class RunPolicy(_Dictable):
    """≙ common.RunPolicy (declared in reference v1 types.go:55-62 and
    implemented in v1alpha2 via batch Jobs). The reference v2 controller never
    implements backoffLimit/activeDeadlineSeconds (SURVEY.md §5.3); this
    framework does, in the controller."""

    clean_pod_policy: Optional[str] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    suspend: bool = False

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RunPolicy":
        sp = d.get("scheduling_policy")
        return RunPolicy(
            clean_pod_policy=d.get("clean_pod_policy"),
            ttl_seconds_after_finished=d.get("ttl_seconds_after_finished"),
            active_deadline_seconds=d.get("active_deadline_seconds"),
            backoff_limit=d.get("backoff_limit"),
            scheduling_policy=SchedulingPolicy.from_dict(sp) if sp else None,
            suspend=d.get("suspend", False),
        )


# ---------------------------------------------------------------------------
# Spec / Status / TPUJob
# ---------------------------------------------------------------------------

@dataclass
class TPUJobSpec(_Dictable):
    """≙ MPIJobSpec (reference v2beta1/types.go:40-80) minus launcher/SSH/MPI
    implementation fields, plus slice topology + elastic policy."""

    slots_per_worker: Optional[int] = None
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    worker: ReplicaSpec = field(default_factory=ReplicaSpec)
    slice: SliceSpec = field(default_factory=SliceSpec)
    elastic: Optional[ElasticPolicy] = None
    # persistent XLA compile cache (ISSUE 16): defaulted ON — warm gang
    # restarts/rescales reuse the node-local cache the executor owns
    # instead of repaying the compile warmup. Projected to workers as
    # $TPUJOB_COMPILE_CACHE; opt out for workloads whose programs are
    # shape-polymorphic enough that cache churn outweighs reuse.
    compile_cache: Optional[bool] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TPUJobSpec":
        el = d.get("elastic")
        cc = d.get("compile_cache")
        return TPUJobSpec(
            slots_per_worker=d.get("slots_per_worker"),
            run_policy=RunPolicy.from_dict(d.get("run_policy", {})),
            worker=ReplicaSpec.from_dict(d.get("worker", {})),
            slice=SliceSpec.from_dict(d.get("slice", {})),
            elastic=ElasticPolicy.from_dict(el) if el else None,
            compile_cache=None if cc is None else bool(cc),
        )


@dataclass
class Condition(_Dictable):
    """≙ common.JobCondition (type/status/reason/message/timestamps)."""

    type: str = ""
    status: bool = False
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0
    last_transition_time: float = 0.0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Condition":
        return Condition(
            type=d.get("type", ""),
            status=bool(d.get("status", False)),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("last_update_time", 0.0),
            last_transition_time=d.get("last_transition_time", 0.0),
        )

    @staticmethod
    def new(ctype: str, status: bool, reason: str, message: str) -> "Condition":
        now = time.time()
        return Condition(ctype, status, reason, message, now, now)


@dataclass
class ReplicaStatus(_Dictable):
    """≙ common.ReplicaStatus: per-replica-type pod phase counts
    (reference updateMPIJobStatus, v2/pkg/controller/mpi_job_controller.go:921-996)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0
    evicted: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ReplicaStatus":
        return ReplicaStatus(
            active=d.get("active", 0),
            succeeded=d.get("succeeded", 0),
            failed=d.get("failed", 0),
            evicted=d.get("evicted", 0),
        )


@dataclass
class JobStatus(_Dictable):
    """≙ common.JobStatus (conditions + replica statuses + timestamps)."""

    conditions: List[Condition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    restart_count: int = 0
    # gang launch generation: advances on EVERY executed whole-gang restart
    # — free preemption restarts included, which restart_count (the
    # backoffLimit budget) deliberately does not count. Stamped onto worker
    # pods as the tpujob.dev/generation label, the observable that lets
    # the chaos invariant checker prove "one generation launching at a
    # time" even across preemption-driven restarts.
    restart_generation: int = 0
    # rendezvous port the controller allocated this job (per-job so two
    # concurrent gangs under one executor never collide on bind; the
    # reference gets isolation for free from per-pod DNS)
    coordinator_port: Optional[int] = None
    # the goodput aggregator's per-job rollup (the workload telemetry
    # plane, ISSUE 15): goodput ratio, step p50, attributed stall buckets
    # incl. controller-charged restart downtime, dominant stall, active
    # straggler — a BOUNDED blob (controller/goodput.py builds it) that
    # `ctl top --jobs` renders straight from the store. Written by the
    # aggregator via uid-pinned status patches; the reconcile loop
    # carries it through untouched.
    train_telemetry: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "JobStatus":
        return JobStatus(
            conditions=[Condition.from_dict(c) for c in d.get("conditions", [])],
            replica_statuses={
                k: ReplicaStatus.from_dict(v) for k, v in d.get("replica_statuses", {}).items()
            },
            start_time=d.get("start_time"),
            completion_time=d.get("completion_time"),
            last_reconcile_time=d.get("last_reconcile_time"),
            restart_count=d.get("restart_count", 0),
            restart_generation=d.get("restart_generation", 0),
            coordinator_port=d.get("coordinator_port"),
            train_telemetry=d.get("train_telemetry"),
        )


@dataclass
class TPUJob(_Dictable):
    api_version: str = API_VERSION
    kind: str = KIND_TPUJOB
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TPUJob":
        return TPUJob(
            api_version=d.get("api_version", d.get("apiVersion", API_VERSION)),
            kind=d.get("kind", KIND_TPUJOB),
            metadata=ObjectMeta.from_dict(d.get("metadata", {})),
            spec=TPUJobSpec.from_dict(d.get("spec", {})),
            status=JobStatus.from_dict(d.get("status", {})),
        )

    # -- naming helpers (≙ the name builders scattered through the reference
    #    controller, e.g. workerName mpi_job_controller.go:1246, svc :1141) --

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def worker_name(self, index: int) -> str:
        return f"{self.metadata.name}-worker-{index}"

    def service_name(self) -> str:
        return f"{self.metadata.name}-worker"

    def config_name(self) -> str:
        return f"{self.metadata.name}-config"

    def podgroup_name(self) -> str:
        return self.metadata.name

    def worker_hostname(self, index: int) -> str:
        """Stable DNS name behind the headless service, ≙ the hostfile entries
        `<job>-worker-i.<job>-worker` (reference newConfigMap,
        v2/pkg/controller/mpi_job_controller.go:1088-1113)."""
        return f"{self.worker_name(index)}.{self.service_name()}"


# ---------------------------------------------------------------------------
# TPUServe: the second workload class — long-lived autoscaled inference gangs
# ---------------------------------------------------------------------------

@dataclass
class AutoscalePolicy(_Dictable):
    """HPA-style autoscaling knobs for a TPUServe.

    The decision function (controller/autoscaler.py recommend()) maps a
    window of observed metrics — aggregate QPS, per-pod queue depth, p99
    latency — to a replica count:

    - ``target_qps_per_replica`` is the primary signal: desired =
      ceil(total_qps / target).
    - ``target_p99_ms`` / ``target_queue_depth`` are breach escalators:
      a window whose worst sample exceeds them bumps desired above the
      QPS answer even when QPS alone looks fine (a hot replica saturating
      on long sequences shows up in latency before throughput).
    - ``scale_up_stabilization_s`` / ``scale_down_stabilization_s`` are
      the HPA stabilization windows: scale-up takes the SMALLEST
      recommendation over its (short) window, scale-down the LARGEST over
      its (long) window — flapping is suppressed structurally, not by a
      cooldown timer alone.
    - ``scale_to_zero_after_s`` (requires ``min_replicas == 0``): a serve
      whose window shows zero traffic for this long releases every chip.
      Scale-FROM-zero needs an arrival-rate signal no pod can report —
      the front door stamps ``tpujob.dev/offered-qps`` on the TPUServe
      (the KEDA-shaped contract) and the autoscaler honors it.
    - ``cold_start_grace_s``: after any scale-UP, scale-down is held this
      long — freshly launched replicas serve no traffic while compiling/
      warming, and their zero-QPS samples would otherwise immediately
      argue the scale-up back down (the classic cold-start flap).

    ``None`` fields take defaults at reconcile time (api/defaults.py), so
    stored specs stay exactly what the user wrote.
    """

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    target_qps_per_replica: Optional[float] = None
    target_p99_ms: Optional[float] = None
    target_queue_depth: Optional[float] = None
    scale_up_stabilization_s: Optional[float] = None
    scale_down_stabilization_s: Optional[float] = None
    scale_to_zero_after_s: Optional[float] = None
    cold_start_grace_s: Optional[float] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AutoscalePolicy":
        return AutoscalePolicy(
            min_replicas=d.get("min_replicas"),
            max_replicas=d.get("max_replicas"),
            target_qps_per_replica=d.get("target_qps_per_replica"),
            target_p99_ms=d.get("target_p99_ms"),
            target_queue_depth=d.get("target_queue_depth"),
            scale_up_stabilization_s=d.get("scale_up_stabilization_s"),
            scale_down_stabilization_s=d.get("scale_down_stabilization_s"),
            scale_to_zero_after_s=d.get("scale_to_zero_after_s"),
            cold_start_grace_s=d.get("cold_start_grace_s"),
        )


@dataclass
class TPUServeSpec(_Dictable):
    """A long-lived inference service: ``replicas`` identical serving
    GANGS of ``workers_per_replica`` hosts each, rolled forward by
    generation when the pod-affecting spec changes, autoscaled when
    ``autoscale`` is set (the autoscaler then owns ``replicas``; the
    user-set value is the starting point).

    Serving defaults to ``priority_class: high`` — a serving scale-up
    that cannot place preempts batch gangs (scheduler/gang.py priority
    preemption), which resume from checkpoint when room frees. That
    asymmetry IS the workload-class distinction: batch tolerates
    displacement, serving traffic does not.
    """

    replicas: Optional[int] = None
    workers_per_replica: Optional[int] = None
    template: PodTemplate = field(default_factory=PodTemplate)
    slice: SliceSpec = field(default_factory=SliceSpec)
    autoscale: Optional[AutoscalePolicy] = None
    priority_class: Optional[str] = None
    # rolling-update shape (kube Deployment semantics): surge replicas
    # above desired while rolling; never more than max_unavailable ready
    # replicas below desired — the default (1, 0) is the zero-unready-
    # window rollout the serve bench asserts
    max_surge: Optional[int] = None
    max_unavailable: Optional[int] = None
    # DisruptionBudget (a PDB riding the rollout machinery, ISSUE 14):
    # the minimum READY replica count that must survive any PLANNED
    # disruption — a maintenance drain may retire a ready replica only
    # when a surged replacement keeps ready_total above this floor.
    # None defaults to replicas - max_unavailable at reconcile time
    # (planned disruption is never allowed to be worse than a rollout);
    # an explicit low value can only RELAX toward that rollout floor,
    # never below it — the zero-unready rollout guarantee always holds.
    disruption_budget: Optional[int] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TPUServeSpec":
        asc = d.get("autoscale")
        return TPUServeSpec(
            replicas=d.get("replicas"),
            workers_per_replica=d.get("workers_per_replica"),
            template=PodTemplate.from_dict(d.get("template", {})),
            slice=SliceSpec.from_dict(d.get("slice", {})),
            autoscale=AutoscalePolicy.from_dict(asc) if asc else None,
            priority_class=d.get("priority_class"),
            max_surge=d.get("max_surge"),
            max_unavailable=d.get("max_unavailable"),
            disruption_budget=d.get("disruption_budget"),
        )


class ServeConditionType:
    """TPUServe condition types (Deployment-shaped, not Job-shaped —
    a serve has no terminal success):

    Available   — ready_replicas >= desired - max_unavailable
    Progressing — a rollout or scale is in flight
    ScaledToZero — desired == 0 and nothing is live (autoscaler idle state)
    """

    AVAILABLE = "Available"
    PROGRESSING = "Progressing"
    SCALED_TO_ZERO = "ScaledToZero"

    ALL_VALUES = (AVAILABLE, PROGRESSING, SCALED_TO_ZERO)


@dataclass
class TPUServeStatus(_Dictable):
    """Mirrors the Deployment status shape the rollout machinery needs:
    counts by readiness and generation, plus the serve generation itself —
    the serving generalization of TPUJob's ``restart_generation`` (there a
    generation is a gang RELAUNCH; here it is a template REVISION, and the
    same ``tpujob.dev/generation`` pod label carries it, so the
    single-generation trail invariants keep holding over serve gangs)."""

    conditions: List[Condition] = field(default_factory=list)
    replicas: int = 0          # live (non-failed) replica gangs observed
    ready_replicas: int = 0    # gangs with every pod Running AND ready
    updated_replicas: int = 0  # live gangs at the current generation
    # template revision counter: bumps when the pod-affecting spec hash
    # changes; stamped on pods as tpujob.dev/generation
    serve_generation: int = 0
    template_hash: str = ""
    # monotonic replica-id allocator — ids are NEVER reused, so a trail
    # can always tell generations' gangs apart by name alone
    next_replica_id: int = 0
    # the autoscaler's latest target (observability; spec.replicas is the
    # authoritative desired count it writes)
    desired_replicas: Optional[int] = None
    last_scale_up_time: Optional[float] = None
    last_scale_down_time: Optional[float] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TPUServeStatus":
        return TPUServeStatus(
            conditions=[Condition.from_dict(c) for c in d.get("conditions", [])],
            replicas=d.get("replicas", 0),
            ready_replicas=d.get("ready_replicas", 0),
            updated_replicas=d.get("updated_replicas", 0),
            serve_generation=d.get("serve_generation", 0),
            template_hash=d.get("template_hash", ""),
            next_replica_id=d.get("next_replica_id", 0),
            desired_replicas=d.get("desired_replicas"),
            last_scale_up_time=d.get("last_scale_up_time"),
            last_scale_down_time=d.get("last_scale_down_time"),
        )


@dataclass
class TPUServe(_Dictable):
    api_version: str = API_VERSION
    kind: str = KIND_TPUSERVE
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUServeSpec = field(default_factory=TPUServeSpec)
    status: TPUServeStatus = field(default_factory=TPUServeStatus)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TPUServe":
        return TPUServe(
            api_version=d.get("api_version", d.get("apiVersion", API_VERSION)),
            kind=d.get("kind", KIND_TPUSERVE),
            metadata=ObjectMeta.from_dict(d.get("metadata", {})),
            spec=TPUServeSpec.from_dict(d.get("spec", {})),
            status=TPUServeStatus.from_dict(d.get("status", {})),
        )

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    # -- naming: one replica gang = one schedulable unit -------------------

    def gang_name(self, replica_id: int) -> str:
        """The replica gang's name — doubles as its PodGroup name and the
        ``tpujob.dev/job-name`` gang-grouping label value, so the gang
        scheduler admits serving replicas with the exact machinery it
        admits batch gangs with."""
        return f"{self.metadata.name}-r{replica_id}"

    def pod_name(self, replica_id: int, index: int) -> str:
        return f"{self.gang_name(replica_id)}-w{index}"


# ---------------------------------------------------------------------------
# Alert: the SLO plane's watchable firing state (ISSUE 13)
# ---------------------------------------------------------------------------

# alerts live in one well-known namespace (like Nodes' pseudo-namespace):
# they are cluster-scoped monitoring state, not tenant objects
ALERT_NAMESPACE = "monitoring"


class AlertState:
    """Alert lifecycle: Firing → Resolved → (a later breach re-fires the
    SAME object, bumping fired_count). There is no terminal state — an
    alert object is the durable history of one objective's breaches."""

    FIRING = "Firing"
    RESOLVED = "Resolved"

    ALL_VALUES = (FIRING, RESOLVED)


@dataclass
class AlertSpec(_Dictable):
    """What the alert is ABOUT — a copy of the objective's identity at
    fire time, so `ctl alerts` renders without the SLO config in hand
    (and an alert outlives a config edit that renamed its objective)."""

    objective: str = ""
    metric: str = ""
    severity: str = "page"   # page | ticket
    description: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AlertSpec":
        return AlertSpec(
            objective=d.get("objective", ""),
            metric=d.get("metric", ""),
            severity=d.get("severity", "page"),
            description=d.get("description", ""),
        )


@dataclass
class AlertStatus(_Dictable):
    """The monitor's view of the breach. Written ONLY via uid-pinned
    status-subresource patches (a recreated same-name alert can never
    absorb a stale monitor's transition — the UID001 discipline)."""

    state: str = AlertState.FIRING
    # which burn-rate window pair tripped ("fast" pages on sudden total
    # breaches, "slow" on sustained budget bleed — SRE-workbook shape)
    window: str = ""
    # worst burn rate observed while firing (budget-multiples/s spend)
    burn: float = 0.0
    since: Optional[float] = None
    resolved_at: Optional[float] = None
    message: str = ""
    # total number of firings this objective has had (a resolve+refire
    # increments; the flap/recurrence signal `ctl alerts` sorts by)
    fired_count: int = 0
    # the flight-recorder bundle dumped when this firing began — the
    # path `ctl trace --last-incident` links
    incident: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AlertStatus":
        return AlertStatus(
            state=d.get("state", AlertState.FIRING),
            window=d.get("window", ""),
            burn=d.get("burn", 0.0),
            since=d.get("since"),
            resolved_at=d.get("resolved_at"),
            message=d.get("message", ""),
            fired_count=d.get("fired_count", 0),
            incident=d.get("incident", ""),
        )


@dataclass
class Alert(_Dictable):
    """A firing/resolved SLO breach, as a first-class watchable store
    object: informers cache it, `ctl alerts` lists it, the watch stream
    carries its transitions, and the firing write is trace-stamped so
    `ctl trace --last-incident` reconstructs what the monitor saw."""

    api_version: str = API_VERSION
    kind: str = KIND_ALERT
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: AlertSpec = field(default_factory=AlertSpec)
    status: AlertStatus = field(default_factory=AlertStatus)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Alert":
        return Alert(
            api_version=d.get("api_version", d.get("apiVersion", API_VERSION)),
            kind=d.get("kind", KIND_ALERT),
            metadata=ObjectMeta.from_dict(d.get("metadata", {})),
            spec=AlertSpec.from_dict(d.get("spec", {})),
            status=AlertStatus.from_dict(d.get("status", {})),
        )

    def is_firing(self) -> bool:
        return self.status.state == AlertState.FIRING
