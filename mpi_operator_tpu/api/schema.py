"""Structural manifest schema: strict parsing + JSON-schema artifact.

≙ the reference's CRD structural OpenAPI schema
(/root/reference/manifests/base/crd.yaml:15-197), which makes the apiserver
reject unknown/typo'd fields before the controller ever sees them. Round 1
lacked this: ``from_dict`` silently dropped unknown keys, so ``slotsPerWorker``
or a typo'd ``chips_per_hosts`` produced a defaulted job with no error
(VERDICT r1 Weak #7). Here the dataclasses themselves are the schema:

- :func:`check_manifest` walks a manifest against the dataclass fields and
  returns dotted-path errors for unknown fields and wrong shapes;
- camelCase spellings of every known field are accepted (k8s manifests are
  camelCase; the native form is snake_case) and normalized before parsing;
- free-form string maps (labels, annotations, env, nodeSelector, resources,
  data) are user content — their keys are never case-converted or checked;
- :func:`parse_tpujob` = normalize → strict-check → ``TPUJob.from_dict``;
- :func:`json_schema` emits the structural JSON Schema artifact
  (deploy/tpujob-schema.json) for external validators.
"""

from __future__ import annotations

import dataclasses
import re
import typing
from typing import Any, Dict, List, Tuple, Type

from mpi_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    PodTemplate,
    TPUJob,
    TPUServe,
)


class ManifestError(ValueError):
    """Raised by parse_tpujob with every problem found, not just the first."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("invalid manifest:\n  " + "\n  ".join(self.errors))


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


# Fields whose values are free-form string maps: keys are user data, never
# schema-checked or case-converted.
_FREEFORM = {
    (ObjectMeta, "labels"),
    (ObjectMeta, "annotations"),
    (Container, "env"),
    (Container, "resources"),
    (PodTemplate, "labels"),
    (PodTemplate, "annotations"),
    (PodTemplate, "node_selector"),
}

# Extra accepted spellings beyond the automatic camelCase of each field.
_EXTRA_ALIASES: Dict[Type, Dict[str, str]] = {
    TPUJob: {"apiVersion": "api_version"},
    TPUServe: {"apiVersion": "api_version"},
    PodTemplate: {"containers": "container"},
}

# Legal k8s fields the native types deliberately don't model: accepted and
# dropped (a container's `name` is meaningless with one container per pod).
_IGNORED = {(Container, "name")}

_PRIMITIVES = {str: "string", int: "integer", float: "number", bool: "boolean"}


def _field_map(cls: Type) -> Dict[str, Tuple[str, Any]]:
    """accepted key → (canonical snake_case name, type)."""
    hints = typing.get_type_hints(cls)
    out: Dict[str, Tuple[str, Any]] = {}
    for f in dataclasses.fields(cls):
        tp = hints.get(f.name, Any)
        out[f.name] = (f.name, tp)
        out[_camel(f.name)] = (f.name, tp)
    for alias, target in _EXTRA_ALIASES.get(cls, {}).items():
        out[alias] = (target, typing.get_type_hints(cls).get(target, Any))
    return out


def _unwrap_optional(tp: Any) -> Any:
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _check_value(cls: Type, fname: str, tp: Any, v: Any, path: str, errors: List[str]) -> Any:
    """Validate + normalize one value; returns the normalized value."""
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if (cls, fname) in _FREEFORM:
        # Container.env additionally accepts the k8s list form
        if cls is Container and fname == "env" and isinstance(v, list):
            return v
        if not isinstance(v, dict):
            errors.append(f"{path}: expected a mapping")
        return v
    if dataclasses.is_dataclass(tp):
        if not isinstance(v, dict):
            errors.append(f"{path}: expected an object")
            return v
        return _check_obj(tp, v, path, errors)
    if origin in (list, typing.List):
        if not isinstance(v, list):
            errors.append(f"{path}: expected a list")
            return v
        (et,) = typing.get_args(tp) or (Any,)
        et = _unwrap_optional(et)
        if dataclasses.is_dataclass(et):
            return [
                _check_obj(et, x, f"{path}[{i}]", errors)
                if isinstance(x, dict)
                else errors.append(f"{path}[{i}]: expected an object") or x
                for i, x in enumerate(v)
            ]
        return v
    if origin in (dict, typing.Dict):
        if not isinstance(v, dict):
            errors.append(f"{path}: expected a mapping")
            return v
        _, vt = typing.get_args(tp) or (str, Any)
        vt = _unwrap_optional(vt)
        if dataclasses.is_dataclass(vt):
            return {
                k: _check_obj(vt, x, f"{path}.{k}", errors)
                if isinstance(x, dict)
                else errors.append(f"{path}.{k}: expected an object") or x
                for k, x in v.items()
            }
        return v
    if tp in _PRIMITIVES and v is not None:
        ok = isinstance(v, tp) or (tp is float and isinstance(v, int))
        # YAML "1" for an int field etc. — be strict: type mismatch is an error
        if tp is bool and isinstance(v, int) and not isinstance(v, bool):
            ok = False
        if not ok:
            errors.append(
                f"{path}: expected {_PRIMITIVES[tp]}, got {type(v).__name__}"
            )
    return v


def _check_obj(cls: Type, d: Dict[str, Any], path: str, errors: List[str]) -> Dict[str, Any]:
    fmap = _field_map(cls)
    out: Dict[str, Any] = {}
    for k, v in d.items():
        if (cls, k) in _IGNORED:
            continue
        hit = fmap.get(k)
        if hit is None:
            # help the user: suggest the snake_case form if that's the issue
            snake = _snake(k)
            hint = f" (did you mean {snake!r}?)" if snake in fmap and snake != k else ""
            errors.append(f"{path}.{k}: unknown field{hint}")
            continue
        fname, tp = hit
        if cls is PodTemplate and k == "containers":
            # k8s plural form: first entry is the main container
            if not isinstance(v, list) or not v:
                errors.append(f"{path}.{k}: expected a non-empty list")
                continue
            if len(v) > 1:
                errors.append(
                    f"{path}.{k}: only one container per worker is supported"
                )
            out[fname] = _check_value(
                cls, fname, Container, v[0], f"{path}.{k}[0]", errors
            )
            continue
        out[fname] = _check_value(cls, fname, tp, v, f"{path}.{k}", errors)
    return out


def check_manifest(
    d: Dict[str, Any], root: Type = TPUJob
) -> Tuple[Dict[str, Any], List[str]]:
    """Strictly check a manifest against ``root``'s dataclass schema
    (TPUJob by default; TPUServe for serving manifests); returns
    (normalized snake_case manifest, errors). Unknown fields at any depth
    are errors."""
    errors: List[str] = []
    if not isinstance(d, dict):
        return {}, ["manifest must be a mapping"]
    norm = _check_obj(root, d, "$", errors)
    return norm, errors


def parse_tpujob(d: Dict[str, Any]) -> TPUJob:
    """normalize → strict-check → TPUJob. Raises ManifestError listing every
    unknown field / shape mismatch (≙ apiserver CRD schema rejection)."""
    norm, errors = check_manifest(d)
    if errors:
        raise ManifestError(errors)
    return TPUJob.from_dict(norm)


def parse_tpuserve(d: Dict[str, Any]) -> TPUServe:
    """normalize → strict-check → TPUServe (the serving workload class's
    admission twin of parse_tpujob; same strictness)."""
    norm, errors = check_manifest(d, root=TPUServe)
    if errors:
        raise ManifestError(errors)
    return TPUServe.from_dict(norm)


# ---------------------------------------------------------------------------
# JSON Schema artifact (deploy/tpujob-schema.json)
# ---------------------------------------------------------------------------

def _type_schema(cls: Type, fname: str, tp: Any, seen: Tuple[Type, ...]) -> Dict[str, Any]:
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if (cls, fname) in _FREEFORM:
        return {"type": "object", "additionalProperties": {"type": "string"}}
    if dataclasses.is_dataclass(tp):
        return _obj_schema(tp, seen)
    if origin in (list, typing.List):
        (et,) = typing.get_args(tp) or (Any,)
        return {"type": "array", "items": _type_schema(cls, fname, et, seen)}
    if origin in (dict, typing.Dict):
        _, vt = typing.get_args(tp) or (str, Any)
        return {
            "type": "object",
            "additionalProperties": _type_schema(cls, fname, vt, seen),
        }
    if tp in _PRIMITIVES:
        return {"type": _PRIMITIVES[tp]}
    return {}


def _obj_schema(cls: Type, seen: Tuple[Type, ...] = ()) -> Dict[str, Any]:
    if cls in seen:
        return {"type": "object"}
    hints = typing.get_type_hints(cls)
    props: Dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        sch = _type_schema(cls, f.name, hints.get(f.name, Any), seen + (cls,))
        props[_camel(f.name)] = sch
        if _camel(f.name) != f.name:
            props[f.name] = sch
    return {
        "type": "object",
        "properties": props,
        "additionalProperties": False,
    }


def json_schema(root: Type = TPUJob) -> Dict[str, Any]:
    """The structural schema artifact (≙ crd.yaml's openAPIV3Schema). Both
    camelCase and snake_case spellings are admitted, mirroring
    check_manifest; everything else is rejected. ``root`` picks the
    workload class (TPUJob or TPUServe)."""
    sch = _obj_schema(root)
    sch["$schema"] = "https://json-schema.org/draft/2020-12/schema"
    sch["title"] = f"{root.__name__} (tpujob.dev/v1)"
    return sch
