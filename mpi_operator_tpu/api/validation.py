"""Validation for TPUJob.

≙ /root/reference/v2/pkg/apis/kubeflow/validation/validation.go:41-128, which
checks (a) the *worst-case generated pod hostname* is a valid DNS-1035 label
(:47-60), (b) enum membership for cleanPodPolicy and mpiImplementation
(:69-79), (c) launcher replicas == 1 (:101-103) and workers >= 1 (:113).

TPU translation: there is no launcher (rule (c) first half vanishes); workers
>= 1 stays; the enum checks cover CleanPodPolicy / RestartPolicy / accelerator;
and we add slice-topology coherence (topology product must equal
workers x chips_per_host) which has no reference analogue because the MPI
cluster shape was never declared, only discovered from the hostfile.

Errors are accumulated field-path style like Go's field.ErrorList
(validation_test.go is table-driven over field paths; tests mirror that).
"""

from __future__ import annotations

import re
from typing import List, Optional

from mpi_operator_tpu.api.types import (
    HOST_BLOCK,
    CleanPodPolicy,
    ElasticPolicy,
    RestartPolicy,
    TPUJob,
    TPUServe,
    compute_host_mesh,
    family_chips_per_host,
    host_block_for,
)

# DNS-1035 label: lowercase alphanumeric + '-', must start with a letter,
# max 63 chars (same rule the reference borrows from apimachinery, :47-60).
_DNS1035 = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")
_MAX_LABEL = 63

# Accelerator families the runtime can build a mesh for ("cpu" = the
# multiprocess CPU test backend of SURVEY.md §4/§7.1). Derived from the
# family geometry table so the two can't drift.
KNOWN_ACCELERATORS = frozenset(HOST_BLOCK)


class ValidationError(ValueError):
    """Carries the accumulated field errors."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__("; ".join(errors))


def _validate_topology(topology: str) -> Optional[List[int]]:
    if not re.fullmatch(r"\d+(x\d+)*", topology):
        return None
    return [int(p) for p in topology.split("x")]


def validate_tpujob(job: TPUJob) -> List[str]:
    """Returns a list of field-path error strings; empty means valid."""
    errs: List[str] = []
    spec = job.spec

    # --- metadata / generated-hostname rule (≙ validation.go:47-60) ---
    name = job.metadata.name
    if not name:
        errs.append("metadata.name: required")
    else:
        replicas = spec.worker.replicas or 1
        worst = job.worker_name(max(replicas - 1, 0))
        if not _DNS1035.match(worst) or len(worst) > _MAX_LABEL:
            errs.append(
                f"metadata.name: generated pod hostname {worst!r} is not a valid "
                f"DNS-1035 label (lowercase alphanumeric/'-', start with letter, "
                f"<= {_MAX_LABEL} chars)"
            )

    # --- slots (≙ validation.go: SlotsPerWorker required/positive) ---
    if spec.slots_per_worker is None:
        errs.append("spec.slots_per_worker: required")
    elif spec.slots_per_worker < 1:
        errs.append("spec.slots_per_worker: must be >= 1")

    # --- enums (≙ validation.go:69-79) ---
    cpp = spec.run_policy.clean_pod_policy
    if cpp is None:
        errs.append("spec.run_policy.clean_pod_policy: required")
    elif cpp not in CleanPodPolicy.ALL_VALUES:
        errs.append(
            f"spec.run_policy.clean_pod_policy: unsupported value {cpp!r}, "
            f"expected one of {list(CleanPodPolicy.ALL_VALUES)}"
        )
    rp = spec.worker.restart_policy
    if rp is not None and rp not in RestartPolicy.ALL_VALUES:
        errs.append(
            f"spec.worker.restart_policy: unsupported value {rp!r}, "
            f"expected one of {list(RestartPolicy.ALL_VALUES)}"
        )
    sp = spec.run_policy.scheduling_policy
    if sp is not None and sp.priority_class:
        from mpi_operator_tpu.scheduler.gang import (
            PRIORITY_CLASSES,
            resolve_priority_class,
        )

        if resolve_priority_class(sp.priority_class) is None:
            errs.append(
                f"spec.run_policy.scheduling_policy.priority_class: unknown "
                f"class {sp.priority_class!r}; expected one of "
                f"{sorted(k for k in PRIORITY_CLASSES if k)} or an integer"
            )
    acc = spec.slice.accelerator
    if acc and acc not in KNOWN_ACCELERATORS:
        # ≙ the MPIImplementation enum check (validation.go:69-79): reject
        # unknown fabric families at admission, not at mesh-construction time.
        errs.append(
            f"spec.slice.accelerator: unsupported value {acc!r}, "
            f"expected one of {sorted(KNOWN_ACCELERATORS)}"
        )

    # --- replicas (≙ validation.go:113 workers >= 1; launcher rule N/A) ---
    if spec.worker.replicas is None:
        errs.append("spec.worker.replicas: required")
    elif spec.worker.replicas < 1:
        errs.append("spec.worker.replicas: must be >= 1")

    # --- run policy numerics ---
    if (
        spec.run_policy.backoff_limit is not None
        and spec.run_policy.backoff_limit < 0
    ):
        errs.append("spec.run_policy.backoff_limit: must be >= 0")
    if (
        spec.run_policy.active_deadline_seconds is not None
        and spec.run_policy.active_deadline_seconds < 0
    ):
        errs.append("spec.run_policy.active_deadline_seconds: must be >= 0")
    if (
        spec.run_policy.ttl_seconds_after_finished is not None
        and spec.run_policy.ttl_seconds_after_finished < 0
    ):
        errs.append("spec.run_policy.ttl_seconds_after_finished: must be >= 0")

    # --- slice coherence (TPU-specific; no reference analogue) ---
    # slots_per_worker (the reference-parity user knob, types.go:44-47) and
    # slice.chips_per_host (what mesh construction reads) name the same
    # physical quantity; when both are set they must agree — divergence has no
    # physical meaning and would split consumers across two truths.
    cph = spec.slice.chips_per_host
    if cph is not None and cph < 1:
        errs.append("spec.slice.chips_per_host: must be >= 1")
    elif cph is not None and spec.slots_per_worker and cph != spec.slots_per_worker:
        errs.append(
            f"spec.slice.chips_per_host: {cph} disagrees with "
            f"spec.slots_per_worker = {spec.slots_per_worker}; they name the "
            f"same quantity (chips per host) — set one or make them equal"
        )
    # TPU hosts own a hardware-fixed chip block (HOST_BLOCK in api.types).
    # host_block_for is the single source of truth for legal per-host
    # geometry — the same helper gang placement and mesh construction use, so
    # a spec that passes admission can always be placed.
    per_host = cph if cph is not None else spec.slots_per_worker
    fam_cph = family_chips_per_host(acc)
    block = host_block_for(acc, per_host) if acc in KNOWN_ACCELERATORS else None
    if acc in KNOWN_ACCELERATORS and per_host and block is None:
        errs.append(
            f"spec.slots_per_worker: {per_host} chips per host is not a legal "
            f"{acc} host configuration (full block "
            f"{'x'.join(map(str, HOST_BLOCK[acc]))}, sub-host values 1 or 2)"
        )
    if (
        fam_cph is not None
        and per_host
        and per_host != fam_cph
        and (spec.worker.replicas or 0) > 1
    ):
        errs.append(
            f"spec.slots_per_worker: multi-host {acc} jobs have {fam_cph} "
            f"chips per host (hosts own a {'x'.join(map(str, HOST_BLOCK[acc]))} "
            f"block), got {per_host} — sub-host slices are single-worker"
        )
    if spec.slice.topology:
        dims = _validate_topology(spec.slice.topology)
        if dims is None:
            errs.append(
                f"spec.slice.topology: malformed {spec.slice.topology!r}, "
                f"expected e.g. '4x4x4'"
            )
        elif spec.worker.replicas and block is not None:
            # identical math to controller.placement.place_workers: the host
            # mesh must exist (per-axis divisibility) and hold exactly
            # `replicas` hosts
            mesh = compute_host_mesh(tuple(dims), block)
            if mesh is None:
                errs.append(
                    f"spec.slice.topology: {spec.slice.topology!r} is not "
                    f"divisible into {acc} host blocks of "
                    f"{'x'.join(map(str, block))}"
                )
            else:
                hosts = 1
                for m in mesh:
                    hosts *= m
                # topology describes ONE slice; a multi-slice job repeats it
                ns_eff = max(spec.slice.num_slices, 1)
                expected = spec.worker.replicas
                if ns_eff > 1 and spec.worker.replicas % ns_eff == 0:
                    expected = spec.worker.replicas // ns_eff
                if hosts != expected:
                    errs.append(
                        f"spec.slice.topology: topology {spec.slice.topology!r} "
                        f"holds {hosts} hosts per slice but the job has "
                        f"{expected} workers per slice"
                    )

    # --- multi-slice coherence (SURVEY.md §5.8: DCN-joined slices) ---
    ns = spec.slice.num_slices
    if ns < 1:
        errs.append("spec.slice.num_slices: must be >= 1")
    elif ns > 1 and spec.worker.replicas:
        if spec.worker.replicas % ns != 0:
            errs.append(
                f"spec.slice.num_slices: {spec.worker.replicas} workers do "
                f"not divide evenly across {ns} slices"
            )

    # --- elastic bounds (≙ horovod -np/min-np/max-np sanity) ---
    el: Optional[ElasticPolicy] = spec.elastic
    if el is not None:
        if el.min_replicas is not None and el.min_replicas < 1:
            errs.append("spec.elastic.min_replicas: must be >= 1")
        if (
            el.min_replicas is not None
            and el.max_replicas is not None
            and el.min_replicas > el.max_replicas
        ):
            errs.append("spec.elastic: min_replicas must be <= max_replicas")
        if spec.worker.replicas:
            if el.max_replicas is not None and spec.worker.replicas > el.max_replicas:
                errs.append("spec.worker.replicas: must be <= spec.elastic.max_replicas")
            if el.min_replicas is not None and spec.worker.replicas < el.min_replicas:
                errs.append("spec.worker.replicas: must be >= spec.elastic.min_replicas")

    return errs


def validate_or_raise(job: TPUJob) -> None:
    errs = validate_tpujob(job)
    if errs:
        raise ValidationError(errs)


def validate_tpuserve(serve: TPUServe) -> List[str]:
    """Field-path errors for a TPUServe; empty means valid. Same posture
    as validate_tpujob: enum membership, generated-name DNS legality, and
    slice/gang geometry coherence checked at admission — a serve that
    passes here can always be placed."""
    errs: List[str] = []
    spec = serve.spec

    name = serve.metadata.name
    if not name:
        errs.append("metadata.name: required")
    else:
        # replica ids are an unbounded monotonic counter: budget the worst
        # generated pod name for a 6-digit id so a long-lived serve can
        # never roll itself into an illegal hostname
        workers = spec.workers_per_replica or 1
        worst = f"{name}-r999999-w{max(workers - 1, 0)}"
        if not _DNS1035.match(worst) or len(worst) > _MAX_LABEL:
            errs.append(
                f"metadata.name: generated pod name {worst!r} is not a valid "
                f"DNS-1035 label (lowercase alphanumeric/'-', start with "
                f"letter, <= {_MAX_LABEL} chars incl. replica suffix budget)"
            )

    if spec.replicas is not None and spec.replicas < 0:
        errs.append("spec.replicas: must be >= 0")
    wpr = spec.workers_per_replica
    if wpr is not None and wpr < 1:
        errs.append("spec.workers_per_replica: must be >= 1")
    if spec.max_surge is not None and spec.max_surge < 1:
        # surge 0 would deadlock the zero-unavailable rollout: nothing may
        # launch above desired AND nothing ready may drain
        errs.append("spec.max_surge: must be >= 1")
    if spec.max_unavailable is not None and spec.max_unavailable < 0:
        errs.append("spec.max_unavailable: must be >= 0")
    if spec.disruption_budget is not None and spec.disruption_budget < 0:
        errs.append("spec.disruption_budget: must be >= 0 (minimum ready "
                    "replicas a planned drain must leave serving)")

    if spec.priority_class:
        from mpi_operator_tpu.scheduler.gang import (
            PRIORITY_CLASSES,
            resolve_priority_class,
        )

        if resolve_priority_class(spec.priority_class) is None:
            errs.append(
                f"spec.priority_class: unknown class "
                f"{spec.priority_class!r}; expected one of "
                f"{sorted(k for k in PRIORITY_CLASSES if k)} or an integer"
            )

    acc = spec.slice.accelerator
    if acc and acc not in KNOWN_ACCELERATORS:
        errs.append(
            f"spec.slice.accelerator: unsupported value {acc!r}, "
            f"expected one of {sorted(KNOWN_ACCELERATORS)}"
        )
    cph = spec.slice.chips_per_host
    if cph is not None and cph < 1:
        errs.append("spec.slice.chips_per_host: must be >= 1")
    block = (
        host_block_for(acc, cph) if acc in KNOWN_ACCELERATORS else None
    )
    if acc in KNOWN_ACCELERATORS and cph and block is None:
        errs.append(
            f"spec.slice.chips_per_host: {cph} chips per host is not a "
            f"legal {acc} host configuration (full block "
            f"{'x'.join(map(str, HOST_BLOCK[acc]))}, sub-host values 1 or 2)"
        )
    fam_cph = family_chips_per_host(acc)
    if (
        fam_cph is not None
        and cph
        and cph != fam_cph
        and (wpr or 0) > 1
    ):
        errs.append(
            f"spec.slice.chips_per_host: multi-host {acc} gangs have "
            f"{fam_cph} chips per host, got {cph} — sub-host slices are "
            f"single-worker"
        )
    if spec.slice.topology:
        dims = _validate_topology(spec.slice.topology)
        if dims is None:
            errs.append(
                f"spec.slice.topology: malformed {spec.slice.topology!r}, "
                f"expected e.g. '4x4x4'"
            )
        elif wpr and block is not None:
            mesh = compute_host_mesh(tuple(dims), block)
            if mesh is None:
                errs.append(
                    f"spec.slice.topology: {spec.slice.topology!r} is not "
                    f"divisible into {acc} host blocks of "
                    f"{'x'.join(map(str, block))}"
                )
            else:
                hosts = 1
                for m in mesh:
                    hosts *= m
                if hosts != wpr:
                    errs.append(
                        f"spec.slice.topology: topology "
                        f"{spec.slice.topology!r} holds {hosts} hosts but "
                        f"each serving replica has {wpr} workers"
                    )
    if spec.slice.num_slices != 1:
        # a serving REPLICA is one gang on one slice; horizontal scale is
        # what replicas are for — a multi-slice single replica would hide
        # the scaling unit from the autoscaler
        errs.append(
            "spec.slice.num_slices: serving replicas are single-slice "
            "gangs (scale horizontally via replicas/autoscale)"
        )

    asc = spec.autoscale
    if asc is not None:
        if asc.min_replicas is not None and asc.min_replicas < 0:
            errs.append("spec.autoscale.min_replicas: must be >= 0")
        if asc.max_replicas is not None and asc.max_replicas < 1:
            errs.append("spec.autoscale.max_replicas: must be >= 1")
        if (
            asc.min_replicas is not None
            and asc.max_replicas is not None
            and asc.min_replicas > asc.max_replicas
        ):
            errs.append(
                "spec.autoscale: min_replicas must be <= max_replicas"
            )
        if (
            asc.target_qps_per_replica is not None
            and asc.target_qps_per_replica <= 0
        ):
            errs.append(
                "spec.autoscale.target_qps_per_replica: must be > 0"
            )
        for fname in ("target_p99_ms", "target_queue_depth",
                      "scale_up_stabilization_s",
                      "scale_down_stabilization_s", "cold_start_grace_s"):
            v = getattr(asc, fname)
            if v is not None and v < 0:
                errs.append(f"spec.autoscale.{fname}: must be >= 0")
        if asc.scale_to_zero_after_s is not None:
            if asc.scale_to_zero_after_s < 0:
                errs.append(
                    "spec.autoscale.scale_to_zero_after_s: must be >= 0"
                )
            if asc.min_replicas is not None and asc.min_replicas > 0:
                errs.append(
                    "spec.autoscale.scale_to_zero_after_s: requires "
                    "min_replicas = 0 (the floor forbids reaching zero)"
                )
    return errs


def validate_serve_or_raise(serve: TPUServe) -> None:
    errs = validate_tpuserve(serve)
    if errs:
        raise ValidationError(errs)
