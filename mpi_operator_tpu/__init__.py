"""mpi_operator_tpu: a TPU-native distributed-training job framework.

A brand-new framework with the capability surface of the Kubeflow MPI Operator
(reference: /root/reference, kubeflow/mpi-operator), redesigned TPU-first:

- Declarative ``TPUJob`` resource (≙ MPIJob, v2/pkg/apis/kubeflow/v2beta1/types.go)
  with defaulting, validation, and a Created/Running/Restarting/Succeeded/Failed
  condition state machine.
- A level-triggered controller/reconciler (≙ v2/pkg/controller/mpi_job_controller.go)
  that materializes headless services, job config, gang-scheduled worker pods and
  mirrors pod phases into job status.
- A multi-host runtime layer replacing mpirun/SSH/hostfiles with coordinator
  rendezvous (``jax.distributed``-style) and XLA collectives over ICI/DCN
  (≙ the OpenMPI/Intel/MPICH + Horovod/NCCL stack the reference delegates to).
- A workload library (data-parallel trainer, ResNet/MNIST/Llama models, ring
  attention sequence parallelism) replacing the reference's Horovod examples.
- Native C++ components (TCP collective runtime + pi smoke test,
  ≙ examples/pi/pi.cc) under native/.
"""

__version__ = "0.1.0"

# Single source of truth for the API group/kind lives in api.types; re-exported
# here for convenience.
from mpi_operator_tpu.api.types import API_VERSION, KIND_TPUJOB  # noqa: E402

GROUP = API_VERSION.split("/", 1)[0]
