"""ResNet v1.5 (50/101) — the headline benchmark workload.

≙ the reference's ``tf_cnn_benchmarks --model=resnet101`` image
(/root/reference/examples/v1/tensorflow-benchmarks.yaml, README.md:166-176;
baseline 154.2 images/sec/GPU, BASELINE.md). TPU-native choices: NHWC layout
(MXU-friendly; the reference runs NCHW for cuDNN), bf16 compute with f32
params and batch-norm statistics, and *global* batch norm for free — under
jit with the batch sharded over data axes, the reduction in the BN mean/var
IS the cross-replica mean, so there is no separate sync-BN machinery.

Functional: ``init``/``apply`` over (params, state) pytrees; state carries BN
running stats (threaded, not mutated)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]

STAGE_BLOCKS = {
    "resnet26": (2, 2, 2, 2),  # test-scale: same bottleneck topology
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
}


@dataclasses.dataclass(frozen=True)
class Config:
    depth: str = "resnet101"
    num_classes: int = 1000
    image_size: int = 224
    channels: int = 3
    width: int = 64
    compute_dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @property
    def stage_blocks(self) -> Tuple[int, ...]:
        return STAGE_BLOCKS[self.depth]


def _he(key, shape):
    fan_in = shape[0] * shape[1] * shape[2] if len(shape) == 4 else shape[0]
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def _bn_init(c):
    return (
        {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)},
        {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)},
    )


def _block_channels(config: Config) -> List[Tuple[int, int, int]]:
    """(in, mid, out) per block, flattened over stages."""
    chans = []
    w = config.width
    c_in = w
    for stage, n_blocks in enumerate(config.stage_blocks):
        mid = w * 2**stage
        out = mid * 4
        for _ in range(n_blocks):
            chans.append((c_in, mid, out))
            c_in = out
    return chans


def init(config: Config, key) -> Tuple[Params, Params]:
    keys = iter(jax.random.split(key, 4 * len(_block_channels(config)) + 8))
    params: Params = {}
    state: Params = {}
    params["stem"] = {"w": _he(next(keys), (7, 7, config.channels, config.width))}
    params["stem_bn"], state["stem_bn"] = _bn_init(config.width)
    for i, (c_in, mid, out) in enumerate(_block_channels(config)):
        blk: Params = {
            "conv1": {"w": _he(next(keys), (1, 1, c_in, mid))},
            "conv2": {"w": _he(next(keys), (3, 3, mid, mid))},
            "conv3": {"w": _he(next(keys), (1, 1, mid, out))},
        }
        blk["bn1"], s1 = _bn_init(mid)
        blk["bn2"], s2 = _bn_init(mid)
        blk["bn3"], s3 = _bn_init(out)
        sblk = {"bn1": s1, "bn2": s2, "bn3": s3}
        if c_in != out:
            blk["proj"] = {"w": _he(next(keys), (1, 1, c_in, out))}
            blk["proj_bn"], sproj = _bn_init(out)
            sblk["proj_bn"] = sproj
        params[f"block{i}"] = blk
        state[f"block{i}"] = sblk
    final = _block_channels(config)[-1][2]
    params["head"] = {
        "w": _he(next(keys), (final, config.num_classes)),
        "b": jnp.zeros((config.num_classes,), jnp.float32),
    }
    return params, state


def logical_axes(config: Config) -> Tuple[Params, Params]:
    conv = {"w": ("conv_kernel", "conv_kernel", "conv_in", "conv_out")}
    bn = {"scale": ("stats",), "bias": ("stats",)}
    bns = {"mean": ("stats",), "var": ("stats",)}
    params: Params = {"stem": conv, "stem_bn": bn}
    state: Params = {"stem_bn": bns}
    for i, (c_in, _, out) in enumerate(_block_channels(config)):
        blk = {"conv1": conv, "conv2": conv, "conv3": conv,
               "bn1": bn, "bn2": bn, "bn3": bn}
        sblk = {"bn1": bns, "bn2": bns, "bn3": bns}
        if c_in != out:
            blk["proj"] = conv
            blk["proj_bn"] = bn
            sblk["proj_bn"] = bns
        params[f"block{i}"] = blk
        state[f"block{i}"] = sblk
    params["head"] = {"w": ("embed", "vocab"), "b": ("vocab",)}
    return params, state


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(w.shape[0] // 2,) * 2, (w.shape[1] // 2,) * 2],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _stem_conv_s2d(x, w):
    """The 7x7/s2 stem conv as a space-to-depth 4x4/s1 conv.

    A 3-channel 7x7 conv is the worst case for the MXU (3 of 128 lanes
    busy) and its filter gradient is the single most HBM-bound op in the
    whole step. Folding a 2x2 spatial block into channels makes the same
    arithmetic a dense 12-channel 4x4 stride-1 conv — identical output,
    identical parameter gradients (the weight transform is linear and
    differentiated through), ~4x the operational intensity. Params stay
    [7,7,3,C]: checkpoints and logical axes are unchanged.

    Derivation: o[i,j] = sum_{u,v in [-3,3]} x[2i+u, 2j+v] w[u+3,v+3].
    With x2[p,q,(di,dj,c)] = x[2p+di, 2q+dj, c], taps split by parity of
    u into (P, di) with u = 2P+di-4 over an 8x8 zero-padded kernel, so
    P spans 4 taps at stride 1 with padding (2,1)."""
    b, h, wid, c = x.shape
    cout = w.shape[-1]
    x2 = x.reshape(b, h // 2, 2, wid // 2, 2, c)
    x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wid // 2, 4 * c)
    wpad = jnp.pad(w, [(1, 0), (1, 0), (0, 0), (0, 0)])  # u+3 = a-1, a in [0,8)
    w2 = wpad.reshape(4, 2, 4, 2, c, cout)
    w2 = w2.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, cout)
    return lax.conv_general_dilated(
        x2, w2, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _maxpool_3x3_s2(x):
    """3x3/s2 maxpool (reduce_window; backward is select-and-scatter).

    Measured on v5e: the native select-and-scatter backward (~880us,
    HBM-bound) beats both alternatives tried — max-of-9-strided-slices
    (+15ms: pad-scatter transposes) and a custom-vjp fused stencil over
    upsampled (y, dy) (+6ms) — so the straightforward lowering stays."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)],
    )


def _bn(config, x, p, s, train):
    """Batch norm tuned for the MXU/HBM balance: statistics are one fused
    f32 pass (E[x] and E[x²] reduce together; jnp.var would re-read the
    activation), and the normalize is a single per-channel FMA in the
    compute dtype — scale/offset are folded in f32 first, so bf16 touches
    only the O(C) constants, never the variance math."""
    if train:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(0, 1, 2))
        mean2 = jnp.mean(jnp.square(x32), axis=(0, 1, 2))
        var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
        mom = config.bn_momentum
        new_s = {
            "mean": mom * s["mean"] + (1 - mom) * mean,
            "var": mom * s["var"] + (1 - mom) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + config.bn_epsilon) * p["scale"]
    offset = p["bias"] - mean * inv
    y = x * inv.astype(x.dtype) + offset.astype(x.dtype)
    return y, new_s


def apply(config: Config, params: Params, state: Params, images, train: bool = True):
    """images [B,H,W,C] → (logits [B,classes] f32, new_state)."""
    dt = config.compute_dtype
    new_state: Params = {}
    x = images.astype(dt)
    if config.image_size % 2 == 0:
        x = _stem_conv_s2d(x, params["stem"]["w"].astype(dt))
    else:  # odd sizes can't space-to-depth; plain strided conv
        x = _conv(x, params["stem"]["w"].astype(dt), stride=2)
    x, new_state["stem_bn"] = _bn(config, x, params["stem_bn"], state["stem_bn"], train)
    x = jax.nn.relu(x)
    x = _maxpool_3x3_s2(x)
    block_idx = 0
    for stage, n_blocks in enumerate(config.stage_blocks):
        for b in range(n_blocks):
            blk = params[f"block{block_idx}"]
            sblk = state[f"block{block_idx}"]
            nblk: Params = {}
            stride = 2 if (stage > 0 and b == 0) else 1
            shortcut = x
            y = _conv(x, blk["conv1"]["w"].astype(dt))
            y, nblk["bn1"] = _bn(config, y, blk["bn1"], sblk["bn1"], train)
            y = jax.nn.relu(y)
            # v1.5: the 3x3 carries the stride (not the 1x1)
            y = _conv(y, blk["conv2"]["w"].astype(dt), stride=stride)
            y, nblk["bn2"] = _bn(config, y, blk["bn2"], sblk["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, blk["conv3"]["w"].astype(dt))
            y, nblk["bn3"] = _bn(config, y, blk["bn3"], sblk["bn3"], train)
            if "proj" in blk:
                shortcut = _conv(x, blk["proj"]["w"].astype(dt), stride=stride)
                shortcut, nblk["proj_bn"] = _bn(
                    config, shortcut, blk["proj_bn"], sblk["proj_bn"], train
                )
            elif stride != 1:  # pragma: no cover - never hit in v1.5 layouts
                shortcut = shortcut[:, ::stride, ::stride]
            x = jax.nn.relu(y + shortcut)
            new_state[f"block{block_idx}"] = nblk
            block_idx += 1
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def loss_fn(config: Config, params: Params, state: Params, batch, train: bool = True):
    logits, new_state = apply(config, params, state, batch["image"], train)
    labels = jax.nn.one_hot(batch["label"], config.num_classes)
    loss = -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))
    return loss, new_state


def flops_per_sample(config: Config) -> float:
    """Analytic forward-pass matmul/conv FLOPs per image (2·MACs)."""
    size = config.image_size
    total = 0.0
    h = size // 2  # stem stride 2
    total += 2 * 49 * config.channels * config.width * h * h
    h = (h + 1) // 2  # maxpool stride 2
    chans = _block_channels(config)
    block_idx = 0
    for stage, n_blocks in enumerate(config.stage_blocks):
        for b in range(n_blocks):
            c_in, mid, out = chans[block_idx]
            stride = 2 if (stage > 0 and b == 0) else 1
            total += 2 * c_in * mid * h * h  # 1x1
            h_out = h // stride
            total += 2 * 9 * mid * mid * h_out * h_out  # 3x3 (strided)
            total += 2 * mid * out * h_out * h_out  # 1x1
            if c_in != out:
                total += 2 * c_in * out * h_out * h_out
            h = h_out
            block_idx += 1
    total += 2 * chans[-1][2] * config.num_classes
    return float(total)
