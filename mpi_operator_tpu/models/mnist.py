"""MNIST convnet (≙ examples/horovod/tensorflow_mnist.py and
examples/mxnet/mxnet_mnist.py in the reference — both small Horovod-DP
convnets; SURVEY.md §2.6).

Same shape as the reference workload: two conv+pool blocks, two dense
layers, softmax cross-entropy. NHWC, bf16 compute."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class Config:
    num_classes: int = 10
    image_size: int = 28
    channels: int = 1
    hidden: int = 128
    compute_dtype: Any = jnp.bfloat16


Params = Dict[str, Any]


def _he(key, shape, fan_in):
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def init(config: Config, key) -> Params:
    k = jax.random.split(key, 4)
    s = config.image_size // 4  # two 2x2 pools
    flat = s * s * 64
    return {
        "conv1": {"w": _he(k[0], (5, 5, config.channels, 32), 25 * config.channels)},
        "conv2": {"w": _he(k[1], (5, 5, 32, 64), 25 * 32)},
        "dense1": {
            "w": _he(k[2], (flat, config.hidden), flat),
            "b": jnp.zeros((config.hidden,), jnp.float32),
        },
        "dense2": {
            "w": _he(k[3], (config.hidden, config.num_classes), config.hidden),
            "b": jnp.zeros((config.num_classes,), jnp.float32),
        },
    }


def logical_axes(config: Config) -> Params:
    return {
        "conv1": {"w": ("conv_kernel", "conv_kernel", "conv_in", "conv_out")},
        "conv2": {"w": ("conv_kernel", "conv_kernel", "conv_in", "conv_out")},
        "dense1": {"w": ("embed", "mlp"), "b": ("mlp",)},
        "dense2": {"w": ("mlp", "vocab"), "b": ("vocab",)},
    }


def _conv_pool(x, w):
    x = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    x = jax.nn.relu(x)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def apply(config: Config, params: Params, images) -> jnp.ndarray:
    """images [B, H, W, C] → logits [B, num_classes]."""
    dt = config.compute_dtype
    x = images.astype(dt)
    x = _conv_pool(x, params["conv1"]["w"].astype(dt))
    x = _conv_pool(x, params["conv2"]["w"].astype(dt))
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense1"]["w"].astype(dt) + params["dense1"]["b"].astype(dt))
    logits = x @ params["dense2"]["w"].astype(dt) + params["dense2"]["b"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(config: Config, params: Params, batch) -> jnp.ndarray:
    logits = apply(config, params, batch["image"])
    labels = jax.nn.one_hot(batch["label"], config.num_classes)
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))


def flops_per_sample(config: Config) -> float:
    s = config.image_size
    c1 = 2 * 25 * config.channels * 32 * s * s
    c2 = 2 * 25 * 32 * 64 * (s // 2) ** 2
    flat = (s // 4) ** 2 * 64
    d1 = 2 * flat * config.hidden
    d2 = 2 * config.hidden * config.num_classes
    return float(c1 + c2 + d1 + d2)
