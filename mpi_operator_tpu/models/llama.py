"""Llama-family decoder (the BASELINE.md Llama-3-8B config).

The reference has no LLM workload — its examples top out at CNN scale
(SURVEY.md §2.6) — but BASELINE.md's acceptance configs require a
Llama-3-8B-class data-parallel + long-context workload. TPU-native design:

- scan-over-layers: all layer params stacked on a leading axis and the
  decoder body is one ``lax.scan`` — O(1) HLO size regardless of depth,
  which is what keeps 32-layer compile times sane on TPU;
- bf16 compute, f32 params/optimizer;
- GQA (grouped-query attention) with RoPE; K/V heads expanded to Q heads
  only at the attention call;
- long context via parallel/ring_attention.py when the mesh has a
  ``sequence`` axis — RoPE and norms operate on global [B,T,D] arrays (XLA
  global-view), only the attention inner loop is manually ring-scheduled;
- logical-axis pytree drives DP/FSDP/TP/SP resharding with zero model edits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from mpi_operator_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
)
from mpi_operator_tpu.parallel.sharding import (
    with_logical_constraint,
    with_logical_constraint_fwd,
)
from mpi_operator_tpu.runtime.topology import AXIS_SEQ

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Config:
    vocab: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    d_ff: int = 14_336
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    # "auto": flash_attention whenever the sequence isn't ring-sharded — the
    # compiled Pallas kernel on TPU, the memory-bounded chunked XLA lowering
    # on other backends (never the dense [T,T] matrix, which OOMs at
    # production sequence lengths). "dense" forces the quadratic oracle
    # (tests/small cases only); "flash" forces the kernel path. A sharded
    # sequence axis always takes the ring — the only exact option there.
    attention_impl: str = "auto"
    # FFN matmul precision (ISSUE 16): "bf16" is the exact baseline; "int8"
    # / "fp8" route w_gate/w_up/w_down (~2/3 of model FLOPs) through
    # kernels.quant_matmul — dynamically quantized forward on the MXU's
    # narrow-dtype tier, full-precision straight-through backward.
    # Attention and the lm_head stay bf16: they are numerically the
    # touchiest matmuls and a minority of the FLOPs.
    matmul_precision: str = "bf16"
    # checkpoint each scan layer: backward stores only the 12-layer stack of
    # [B,T,D] layer inputs instead of every intra-layer intermediate — the
    # remat that actually bounds peak HBM for deep stacks (a whole-loss
    # jax.checkpoint would not: its backward recomputation re-materializes
    # all layer intermediates at once)
    remat_layers: bool = False

    def __post_init__(self):
        if self.attention_impl not in ("auto", "dense", "flash"):
            raise ValueError(
                f"attention_impl={self.attention_impl!r}; "
                "expected auto|dense|flash"
            )
        if self.matmul_precision not in ("bf16", "int8", "fp8"):
            raise ValueError(
                f"matmul_precision={self.matmul_precision!r}; "
                "expected bf16|int8|fp8"
            )

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim


def llama3_8b() -> Config:
    return Config()


def bench_single_chip() -> Config:
    """Llama-3-architecture decoder (~0.79B params) sized so AdamW training
    fits one 16 GiB v5e chip: every matmul dim a multiple of 128 (MXU tiles),
    GQA 4:1, d_ff = 3.5x like the 8B config. The compute-bound MFU
    demonstration workload for bench.py's llama mode."""
    return Config(
        vocab=32_768, d_model=2048, n_layers=12, n_heads=16, n_kv_heads=4,
        head_dim=128, d_ff=7168, remat_layers=True,
    )


def bench_long_context() -> Config:
    """The bench_single_chip architecture with a 16k vocab: the embed +
    lm_head state (params + AdamW moments + grads, ~1 GB f32) is what
    doesn't fit next to 16k-token activations on a 16 GiB chip. Used by
    bench.py's llama mode above 8k sequence."""
    return dataclasses.replace(bench_single_chip(), vocab=16_384)


def tiny(vocab: int = 256) -> Config:
    """Test-scale config with the same architecture (GQA ratio included)."""
    return Config(
        vocab=vocab, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=16, d_ff=128, rope_theta=10_000.0,
    )


def _normal(key, shape, scale):
    return jax.random.normal(key, shape, jnp.float32) * scale


def init(config: Config, key) -> Params:
    c = config
    ke, kl, kh = jax.random.split(key, 3)
    lk = jax.random.split(kl, 7)
    n, d = c.n_layers, c.d_model
    s_d = d**-0.5
    s_ff = c.d_ff**-0.5
    s_q = c.q_dim**-0.5
    return {
        "embed": {"w": _normal(ke, (c.vocab, d), 1.0)},
        # all layers stacked on axis 0 → lax.scan over the leading axis
        "layers": {
            "attn_norm": {"scale": jnp.ones((n, d), jnp.float32)},
            "wq": {"w": _normal(lk[0], (n, d, c.q_dim), s_d)},
            "wk": {"w": _normal(lk[1], (n, d, c.kv_dim), s_d)},
            "wv": {"w": _normal(lk[2], (n, d, c.kv_dim), s_d)},
            "wo": {"w": _normal(lk[3], (n, c.q_dim, d), s_q)},
            "mlp_norm": {"scale": jnp.ones((n, d), jnp.float32)},
            "w_gate": {"w": _normal(lk[4], (n, d, c.d_ff), s_d)},
            "w_up": {"w": _normal(lk[5], (n, d, c.d_ff), s_d)},
            "w_down": {"w": _normal(lk[6], (n, c.d_ff, d), s_ff)},
        },
        "final_norm": {"scale": jnp.ones((d,), jnp.float32)},
        "lm_head": {"w": _normal(kh, (d, c.vocab), s_d)},
    }


def logical_axes(config: Config) -> Params:
    # leading "layers" stack axis is always replicated (None)
    return {
        "embed": {"w": ("vocab", "embed")},
        "layers": {
            "attn_norm": {"scale": (None, "stats")},
            "wq": {"w": (None, "embed", "heads")},
            "wk": {"w": (None, "embed", "kv_heads")},
            "wv": {"w": (None, "embed", "kv_heads")},
            "wo": {"w": (None, "heads", "embed")},
            "mlp_norm": {"scale": (None, "stats")},
            "w_gate": {"w": (None, "embed", "mlp")},
            "w_up": {"w": (None, "embed", "mlp")},
            "w_down": {"w": (None, "mlp", "embed")},
        },
        "final_norm": {"scale": ("stats",)},
        "lm_head": {"w": ("embed", "vocab")},
    }


def _rmsnorm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def _rope_tables(t, dh, theta, dtype):
    """cos/sin rotation tables [T, Dh/2] for global positions 0..T-1
    (arrays are global-view; sequence sharding is XLA's problem, not
    RoPE's). Shared by both layout variants so the math can never drift."""
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def _rotate(x, cos, sin):
    x1, x2 = x[..., : x.shape[-1] // 2], x[..., x.shape[-1] // 2 :]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def _rope(x, theta):
    """RoPE, model layout: x [B,T,H,Dh], positions along axis 1."""
    cos, sin = _rope_tables(x.shape[1], x.shape[-1], theta, x.dtype)
    return _rotate(x, cos[None, :, None, :], sin[None, :, None, :])


def _rope_bhtd(x, theta):
    """RoPE, kernel heads-major layout: x [B,H,T,Dh], positions along
    axis 2 (same tables, different broadcast)."""
    cos, sin = _rope_tables(x.shape[2], x.shape[-1], theta, x.dtype)
    return _rotate(x, cos[None, None], sin[None, None])


def apply(
    config: Config,
    params: Params,
    tokens,
    *,
    mesh=None,
    rules=None,
    return_features=False,
) -> jnp.ndarray:
    """tokens [B,T] int32 → logits [B,T,vocab] f32 (or the final-norm
    features [B,T,d_model] with ``return_features`` — the long-context
    loss applies the lm_head blockwise instead).

    With a mesh that has a ``sequence`` axis, attention runs as ring
    attention over ICI; otherwise dense causal attention. All other ops are
    global-view and sharded by constraint propagation."""
    c = config
    dt = c.compute_dtype

    def constrain(x, axes):
        if mesh is None:
            return x
        return with_logical_constraint(x, axes, rules=rules, mesh=mesh)

    def constrain_fwd(x, axes):
        # forward-only at activation boundaries: the cotangent arrives
        # sharded by the weight layout (d_model over fsdp); forcing the
        # batch-sharded primal spec onto it makes the partitioner fall back
        # to replicate-then-repartition (involuntary full remat)
        if mesh is None:
            return x
        return with_logical_constraint_fwd(x, axes, rules=rules, mesh=mesh)

    # gather from a table laid out for lookup: vocab stays tensor-sharded
    # (XLA's TP-embedding gather + psum), the embed dim is gathered over
    # fsdp VOLUNTARILY here — otherwise the partitioner reshards the gather
    # output [.,.,fsdp] → [batch-sharded] by full rematerialization
    emb = constrain(params["embed"]["w"].astype(dt), ["vocab", None])
    x = emb[tokens]
    x = constrain_fwd(x, ["batch", "seq", "embed"])

    def layer(carry, lp):
        h = carry
        y = _rmsnorm(h, lp["attn_norm"]["scale"], c.norm_eps)
        b, t, _ = y.shape
        # K/V stay at n_kv_heads: every attention path is GQA-aware, so the
        # ring never carries expanded K/V
        seq_sharded = (
            mesh is not None
            and AXIS_SEQ in mesh.axis_names
            and mesh.shape[AXIS_SEQ] > 1
        )
        use_flash = not seq_sharded and c.attention_impl != "dense"
        if use_flash:
            from mpi_operator_tpu.kernels import flash_attention

            # heads-major end to end: project straight into the kernel's
            # [B,H,T,Dh] layout via einsum (the transpose folds into the
            # matmul) and fold the attention output into wo the same way —
            # no standalone [B,T,H,D]↔[B,H,T,D] copies around the kernel.
            # auto/flash: the kernel on TPU, chunked XLA elsewhere; mesh
            # passed through (the pallas call is not SPMD-partitionable).
            wq3 = lp["wq"]["w"].astype(dt).reshape(-1, c.n_heads, c.head_dim)
            wk3 = lp["wk"]["w"].astype(dt).reshape(-1, c.n_kv_heads, c.head_dim)
            wv3 = lp["wv"]["w"].astype(dt).reshape(-1, c.n_kv_heads, c.head_dim)
            q = _rope_bhtd(jnp.einsum("btd,dhx->bhtx", y, wq3), c.rope_theta)
            k = _rope_bhtd(jnp.einsum("btd,dhx->bhtx", y, wk3), c.rope_theta)
            v = jnp.einsum("btd,dhx->bhtx", y, wv3)
            attn = flash_attention(
                q, k, v, causal=True, scale=c.head_dim**-0.5, mesh=mesh,
                layout="bhtd",
            )
            wo3 = lp["wo"]["w"].astype(dt).reshape(c.n_heads, c.head_dim, -1)
            h = h + jnp.einsum("bhtx,hxd->btd", attn, wo3)
        else:
            q = (y @ lp["wq"]["w"].astype(dt)).reshape(b, t, c.n_heads, c.head_dim)
            k = (y @ lp["wk"]["w"].astype(dt)).reshape(b, t, c.n_kv_heads, c.head_dim)
            v = (y @ lp["wv"]["w"].astype(dt)).reshape(b, t, c.n_kv_heads, c.head_dim)
            q = _rope(q, c.rope_theta)
            k = _rope(k, c.rope_theta)
            if seq_sharded:
                # ring attention: the only exact option over a sharded sequence
                attn = ring_attention(q, k, v, mesh, causal=True)
            else:
                attn = dense_attention(
                    q, k, v, causal=True, scale=c.head_dim**-0.5
                )
            attn = attn.reshape(b, t, c.q_dim)
            h = h + attn @ lp["wo"]["w"].astype(dt)
        h = constrain_fwd(h, ["batch", "seq", "embed"])
        y = _rmsnorm(h, lp["mlp_norm"]["scale"], c.norm_eps)
        if c.matmul_precision == "bf16":
            gate = jax.nn.silu(y @ lp["w_gate"]["w"].astype(dt))
            up = y @ lp["w_up"]["w"].astype(dt)
            h = h + (gate * up) @ lp["w_down"]["w"].astype(dt)
        else:
            # quantized FFN (config-gated): forward contraction on the
            # int8/fp8 MXU tier, backward full-precision (custom_vjp in
            # kernels.quant_matmul — the straight-through estimator)
            from mpi_operator_tpu.kernels.quant_matmul import quant_matmul

            mp = c.matmul_precision
            gate = jax.nn.silu(
                quant_matmul(y, lp["w_gate"]["w"].astype(dt), precision=mp)
            )
            up = quant_matmul(y, lp["w_up"]["w"].astype(dt), precision=mp)
            h = h + quant_matmul(
                gate * up, lp["w_down"]["w"].astype(dt), precision=mp
            )
        h = constrain_fwd(h, ["batch", "seq", "embed"])
        return h, None

    if c.remat_layers:
        # save the flash kernel's (o, lse) residuals across the remat
        # boundary: recomputing them in the backward costs a full kernel
        # pass (~4% of the llama step on v5e) for ~70MB/layer of HBM
        layer = jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_o", "flash_lse"
            ),
        )
    x, _ = lax.scan(layer, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"]["scale"], c.norm_eps)
    if return_features:
        return x
    logits = x @ params["lm_head"]["w"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(
    config: Config,
    params: Params,
    batch,
    *,
    mesh=None,
    rules=None,
    ce_chunk: int = 2048,
) -> jnp.ndarray:
    """Next-token cross-entropy. batch = {"tokens": [B,T]}; position t
    predicts token t+1; the final position is dropped.

    Above ``ce_chunk`` positions the loss is computed blockwise over the
    sequence (checkpointed lax.map): the [B,T,vocab] f32 logits plus their
    log-softmax are each >2 GB at 16k×32k-vocab — materializing them is
    what OOMs long-context training, not the attention. Chunking keeps CE
    memory at O(B·chunk·vocab) with exact results."""
    tokens = batch["tokens"]
    t = tokens.shape[1]
    if t - 1 <= ce_chunk:
        logits = apply(config, params, tokens, mesh=mesh, rules=rules)
        targets = tokens[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1])
        ll = jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    feats = apply(
        config, params, tokens, mesh=mesh, rules=rules, return_features=True
    )  # [B, T, D] compute dtype
    head = params["lm_head"]["w"]
    # shift targets by roll instead of slicing feats[:-1]/tokens[1:]:
    # keeping T intact aligns chunk boundaries with the (typically
    # power-of-two) sequence length so no repad is needed; the final
    # position is masked out below
    b, t_full = tokens.shape
    y = jnp.roll(tokens, -1, axis=1)
    n = t_full - 1  # real prediction positions
    n_chunks = -(-t_full // ce_chunk)
    pad = n_chunks * ce_chunk - t_full
    x = feats
    if pad:
        x = jnp.pad(x, [(0, 0), (0, pad), (0, 0)])
        y = jnp.pad(y, [(0, 0), (0, pad)])
    xr = x.reshape(b, n_chunks, ce_chunk, -1).transpose(1, 0, 2, 3)
    yr = y.reshape(b, n_chunks, ce_chunk).transpose(1, 0, 2)
    valid = (
        jnp.arange(n_chunks * ce_chunk).reshape(n_chunks, 1, ce_chunk) < n
    )

    @jax.checkpoint
    def chunk_nll(xc, yc, vc):
        logits = (xc @ head.astype(xc.dtype)).astype(jnp.float32)
        lp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(lp, yc[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(vc, ll, 0.0))

    totals = lax.map(lambda args: chunk_nll(*args), (xr, yr, valid))
    return -jnp.sum(totals) / (b * n)


def param_count(config: Config) -> int:
    c = config
    per_layer = (
        c.d_model * (c.q_dim + 2 * c.kv_dim)
        + c.q_dim * c.d_model
        + 3 * c.d_model * c.d_ff
        + 2 * c.d_model
    )
    return (
        c.vocab * c.d_model
        + c.n_layers * per_layer
        + c.d_model
        + c.d_model * c.vocab
    )


def flops_per_token(config: Config, seq_len: int) -> float:
    """Forward matmul FLOPs per token (2·MACs); attention term included."""
    c = config
    matmul_params = (
        c.d_model * (c.q_dim + 2 * c.kv_dim)
        + c.q_dim * c.d_model
        + 3 * c.d_model * c.d_ff
    )
    per_layer = 2 * matmul_params + 4 * seq_len * c.q_dim  # scores + PV
    return float(c.n_layers * per_layer + 2 * c.d_model * c.vocab)
