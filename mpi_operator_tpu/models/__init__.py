"""Workload model library (≙ the reference's examples/ images).

The reference ships training workloads as opaque container images — TF
benchmarks ResNet-101, Horovod TF MNIST, MXNet MNIST
(/root/reference/examples/, SURVEY.md §2.6). Here the workloads are a
first-class library, TPU-native:

- plain functional JAX (init/apply pairs over param pytrees) so pjit sees
  every array;
- every model exposes a ``logical_axes`` pytree (same structure as params)
  consumed by parallel/sharding.py — the same model runs pure-DP, FSDP, TP,
  or sequence-parallel by swapping the rule table, never by editing the model;
- bf16 compute / f32 params+optimizer by default (MXU-native);
- ``flops_per_sample`` accounting so bench.py can report MFU.

Families: mnist (≙ examples/horovod/tensorflow_mnist.py and the MXNet MNIST),
resnet (≙ tf_cnn_benchmarks --model=resnet101, the headline benchmark),
llama (the BASELINE.md Llama-3-8B DP/long-context config).
"""

from mpi_operator_tpu.models import llama, mnist, resnet

MODELS = {
    "mnist": mnist,
    "resnet50": resnet,
    "resnet101": resnet,
    "llama": llama,
}

__all__ = ["mnist", "resnet", "llama", "MODELS"]
