"""Workload model library (≙ the reference's examples/ images).

The reference ships training workloads as opaque container images — TF
benchmarks ResNet-101, Horovod TF MNIST, MXNet MNIST
(/root/reference/examples/, SURVEY.md §2.6). Here the workloads are a
first-class library, TPU-native:

- plain functional JAX (init/apply pairs over param pytrees) so pjit sees
  every array;
- every model exposes a ``logical_axes`` pytree (same structure as params)
  consumed by parallel/sharding.py — the same model runs pure-DP, FSDP, TP,
  or sequence-parallel by swapping the rule table, never by editing the model;
- bf16 compute / f32 params+optimizer by default (MXU-native);
- ``flops_per_sample`` accounting so bench.py can report MFU.

Families: mnist (≙ examples/horovod/tensorflow_mnist.py and the MXNet MNIST),
resnet (≙ tf_cnn_benchmarks --model=resnet101, the headline benchmark),
llama (the BASELINE.md Llama-3-8B DP/long-context config).
"""

from mpi_operator_tpu.models import llama, mnist, resnet

# name → (module, config factory); the factory bakes in the depth/preset so
# registry users can't get a module whose default Config contradicts the name
MODELS = {
    "mnist": (mnist, mnist.Config),
    "resnet50": (resnet, lambda: resnet.Config(depth="resnet50")),
    "resnet101": (resnet, lambda: resnet.Config(depth="resnet101")),
    "llama3-8b": (llama, llama.llama3_8b),
    "llama-tiny": (llama, llama.tiny),
}

__all__ = ["mnist", "resnet", "llama", "MODELS"]
