"""Parallelism layer: collectives, sharding rules, sequence parallelism.

The reference's collective fabric is external — OpenMPI/Intel/MPICH plus
Horovod's NCCL ring, shipped inside user images and merely *wired up* by the
operator (SURVEY.md §1 layer 6, §5.8). Here the fabric is XLA itself and this
package is its thin, named API:

- :mod:`collectives` — psum/all_gather/reduce_scatter/ppermute wrappers with
  the MPI correspondence documented per-op (the capability contract of
  /root/reference/examples/pi/pi.cc's ``MPI_Reduce`` and Horovod's allreduce).
- :mod:`sharding` — logical-axis → mesh-axis rules so models declare *what*
  an axis means and deployment picks *where* it shards.
- :mod:`ring_attention` — blockwise ring attention over the ``sequence``
  mesh axis via ``ppermute`` (the long-context capability; SURVEY.md §5.7).
"""

from mpi_operator_tpu.parallel import collectives
from mpi_operator_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_spec,
    named_sharding,
    with_logical_constraint,
)
from mpi_operator_tpu.parallel.ring_attention import ring_attention

__all__ = [
    "collectives",
    "DEFAULT_RULES",
    "logical_spec",
    "named_sharding",
    "with_logical_constraint",
    "ring_attention",
]
