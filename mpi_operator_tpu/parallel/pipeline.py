"""SPMD pipeline parallelism over the ``pipe`` mesh axis.

Absent from the reference (its ranks are workload-agnostic MPI processes;
SURVEY.md §2.5 row TP/PP/SP/EP: "No") — here it's a first-class schedule.
TPU-native shape: instead of a per-stage program + point-to-point sends (the
GPU idiom), ONE program runs on every device under shard_map; the layer
stack is sharded over ``pipe`` (each device owns n_layers/S consecutive
layers) and microbatch activations rotate stage-to-stage with neighbour
``ppermute`` hops — a GPipe schedule with S+M-1 ticks, collectives riding
ICI.

The schedule works on any per-stage function; models/llama.py plugs its
scanned layer body in directly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from mpi_operator_tpu.jaxcompat import shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_operator_tpu.parallel import collectives as c
from mpi_operator_tpu.runtime.topology import AXIS_PIPE


def pipeline_spmd(
    stage_fn: Callable,
    stage_params,
    microbatches,
    *,
    axis_name: str = AXIS_PIPE,
):
    """Run under shard_map. Executes the GPipe schedule:

    - ``stage_fn(stage_params, x) -> y``: this device's slice of the model
      (its layers), applied to one microbatch of activations.
    - ``microbatches``: [M, ...] stacked microbatch inputs (every stage
      receives the same array; only stage 0 consumes it).

    Returns [M, ...] outputs as produced by the LAST stage (other stages
    return zeros — callers psum or slice; keeping it zero elsewhere makes
    the loss reduction a plain psum over the pipe axis).

    Schedule: T = M + S - 1 ticks. At tick t, stage s processes microbatch
    t - s (when in range). Activations hop s→s+1 between ticks via a single
    ICI ppermute.
    """
    n_stages = c.axis_size_static(axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    x_shape = microbatches.shape[1:]

    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        inflight, outputs = carry
        # stage 0 injects microbatch t (if any); others take the hopped-in
        # activation from the previous tick
        mb_idx = jnp.clip(t, 0, m - 1)
        fresh = microbatches[mb_idx]
        x = jnp.where(stage == 0, fresh, inflight)
        y = stage_fn(stage_params, x)
        # last stage banks its result for microbatch t - (S-1); masked write
        # (not lax.cond) keeps both paths the same varying type
        out_idx = t - (n_stages - 1)
        is_last = stage == n_stages - 1
        valid = jnp.logical_and(is_last, out_idx >= 0)
        safe_idx = jnp.clip(out_idx, 0, m - 1)
        banked = jnp.where(valid, y, outputs[safe_idx])
        outputs = outputs.at[safe_idx].set(banked)
        # hop activations to the next stage (last→0 wraps but stage 0
        # ignores what it receives, so the wrap is harmless)
        inflight = lax.ppermute(y, axis_name, fwd)
        return (inflight, outputs), None

    # carries must be device-varying over the pipe axis AND inherit the
    # microbatches' own varying axes (e.g. data sharding) from tick 0 —
    # scan type-checks carry vma under shard_map
    inflight0 = lax.pcast(microbatches[0] * 0, (axis_name,), to="varying")
    outputs0 = lax.pcast(microbatches * 0, (axis_name,), to="varying")
    (_, outputs), _ = lax.scan(
        tick, (inflight0, outputs0), jnp.arange(m + n_stages - 1)
    )
    # zero everywhere except the last stage
    return jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))


def run_pipeline(
    stage_fn: Callable,
    stacked_params,
    batch,
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis_name: str = AXIS_PIPE,
    batch_axes=("data", "fsdp"),
):
    """Global-view wrapper: shards ``stacked_params`` (leading dim = stages)
    over the pipe axis and ``batch`` (leading dim = global batch) into
    microbatches, runs the schedule, returns [B, ...] outputs (from the
    final stage, broadcast to all stages via psum of the zero-padded
    outputs)."""
    if axis_name not in mesh.axis_names or mesh.shape[axis_name] == 1:
        # no pipelining in this mesh: apply all stages sequentially
        def all_stages(x):
            def body(h, p):
                return stage_fn(p, h), None

            h, _ = lax.scan(body, x, stacked_params)
            return h

        return all_stages(batch)

    b = batch.shape[0]
    mb = b // n_microbatches
    micro = batch.reshape((n_microbatches, mb) + batch.shape[1:])

    def shard_body(params, micro_in):
        # this device's param slice keeps a leading local-layers dim; a
        # local scan turns the per-layer stage_fn into this stage's body
        def local_stage(p_local, x):
            def body(h, p):
                return stage_fn(p, h), None

            h, _ = lax.scan(body, x, p_local)
            return h

        outs = pipeline_spmd(
            local_stage, params, micro_in, axis_name=axis_name
        )
        # every stage holds zeros except the last → psum broadcasts the
        # result to all stages (cheap: one pass over the output bytes)
        outs = lax.psum(outs, axis_name)
        return outs

    param_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    # microbatch dim 1 (the per-microbatch batch dim) shards over the data
    # axes so a data×pipe mesh does DP beside PP instead of replicating
    b_part = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    micro_spec = P(None, b_part, *(None,) * (micro.ndim - 2))
    out = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(param_spec, micro_spec),
        out_specs=micro_spec,
    )(stacked_params, micro)
    return out.reshape((b,) + batch.shape[1:])
