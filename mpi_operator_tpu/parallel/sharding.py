"""Logical-axis sharding rules.

Models in this framework never name mesh axes directly: they annotate arrays
with *logical* axes ("batch", "embed", "heads", …) and a rule table maps
those to the mesh axes of runtime/topology.py. Deployment then re-shards the
same model from pure-DP (the reference's only strategy, SURVEY.md §2.5) to
FSDP/TP/SP/EP mixes by swapping the rule table — no model edits. This is the
capability the reference cannot express (its ranks are placement-flat MPI
processes); here it's the default.

A rule maps a logical axis to: a mesh axis name, a tuple of mesh axis names
(the array axis is sharded over their product), or None (replicated).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from mpi_operator_tpu.runtime.topology import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
)

Rule = Union[str, Tuple[str, ...], None]
Rules = Dict[str, Rule]

# The standard table. "batch" shards over both DP-ish axes (data carries the
# plain-DP component, fsdp the ZeRO component); parameter logical axes shard
# over fsdp (ZeRO-3 gather) and/or tensor (megatron split); "seq" is the
# ring-attention axis.
DEFAULT_RULES: Rules = {
    "batch": (AXIS_DATA, AXIS_FSDP),
    "seq": AXIS_SEQ,
    "embed": AXIS_FSDP,
    "mlp": AXIS_TENSOR,
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "qkv": None,
    "head_dim": None,
    "vocab": AXIS_TENSOR,
    "expert": AXIS_EXPERT,
    "conv_kernel": None,
    "conv_in": None,
    "conv_out": AXIS_FSDP,
    "stats": None,
}


def logical_spec(
    logical_axes: Sequence[Optional[str]], rules: Optional[Rules] = None
) -> PartitionSpec:
    """(logical axis per array dim) → PartitionSpec via the rule table.

    A mesh axis may appear at most once in a PartitionSpec; when two logical
    axes map to the same mesh axis the later one degrades to replicated
    (matching flax's logical-axis semantics)."""
    rules = DEFAULT_RULES if rules is None else rules
    used: set = set()
    parts = []
    for ax in logical_axes:
        rule = rules.get(ax) if ax is not None else None
        if rule is None:
            parts.append(None)
            continue
        mesh_axes = (rule,) if isinstance(rule, str) else tuple(rule)
        fresh = tuple(m for m in mesh_axes if m not in used)
        if not fresh:
            parts.append(None)
            continue
        used.update(fresh)
        parts.append(fresh[0] if len(fresh) == 1 else fresh)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def mesh_filtered_spec(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Drop mesh axes the given mesh doesn't have (so one rule table serves
    meshes of any dimensionality — a pure-DP mesh simply ignores tensor/seq
    rules)."""
    parts = []
    for p in spec:
        if p is None:
            parts.append(None)
        elif isinstance(p, str):
            parts.append(p if p in mesh.axis_names else None)
        else:
            kept = tuple(m for m in p if m in mesh.axis_names)
            parts.append(kept[0] if len(kept) == 1 else (kept or None))
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Rules] = None,
) -> NamedSharding:
    return NamedSharding(mesh, mesh_filtered_spec(logical_spec(logical_axes, rules), mesh))


def with_logical_constraint(
    x,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Rules] = None,
    mesh: Optional[Mesh] = None,
):
    """``with_sharding_constraint`` by logical axes — the in-jit annotation
    that steers XLA's sharding propagation at activation boundaries (the knob
    deciding which collectives get inserted and where resharding happens).

    ``mesh`` is the trace-time mesh (pass it explicitly from the trainer; it
    is static). Without one, falls back to the ambient abstract mesh if set,
    else no-op — so model code runs unchanged on a single device."""
    import jax

    if mesh is None:
        am = jax.sharding.get_abstract_mesh()
        if am is None or not am.axis_names:
            return x
        mesh = am
    spec = mesh_filtered_spec(logical_spec(logical_axes, rules), mesh)
    if isinstance(mesh, Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def with_logical_constraint_fwd(
    x,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Rules] = None,
    mesh: Optional[Mesh] = None,
):
    """Forward-only logical constraint: the primal is annotated, the
    cotangent passes through UNconstrained.

    ``with_sharding_constraint`` transposes to the same constraint on the
    cotangent — but activation gradients often arrive sharded by the
    *weight* layout (e.g. d_model sharded over fsdp out of a ZeRO matmul
    backward) while the primal constraint shards the *batch* dim over
    fsdp. Forcing that transition makes the SPMD partitioner fall back to
    "replicate then repartition" ([SPMD] Involuntary full
    rematerialization). Leaving the backward free lets XLA keep the
    natural cotangent sharding and pick the cheap collective itself."""
    import jax

    @jax.custom_vjp
    def _constrained(y):
        return with_logical_constraint(y, logical_axes, rules=rules, mesh=mesh)

    def _fwd(y):
        return _constrained(y), None

    def _bwd(_, g):
        return (g,)

    _constrained.defvjp(_fwd, _bwd)
    return _constrained(x)
