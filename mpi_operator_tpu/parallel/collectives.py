"""Named collective API over XLA (≙ the MPI/Horovod verbs).

Capability mapping from the reference stack (SURVEY.md §5.8) — each function
notes the MPI/Horovod verb it replaces. All of these are XLA collectives:
inside ``jit`` under ``shard_map``/``pjit`` they lower to ICI/DCN primitives
and fuse with surrounding compute; none of them touch the host.

| here              | reference stack                                        |
|-------------------|--------------------------------------------------------|
| ``psum``          | ``MPI_Allreduce(SUM)`` / Horovod allreduce (ring/NCCL) |
| ``pmean``         | Horovod's averaged allreduce (DistributedOptimizer)    |
| ``reduce_to_root``| ``MPI_Reduce`` to rank 0 (examples/pi/pi.cc:44)        |
| ``all_gather``    | ``MPI_Allgather``                                      |
| ``reduce_scatter``| ``MPI_Reduce_scatter``                                 |
| ``ring_shift``    | the ring topology Horovod builds internally            |
| ``all_to_all``    | ``MPI_Alltoall`` (MoE dispatch)                        |
| ``broadcast_root``| ``MPI_Bcast`` / ``hvd.broadcast_global_variables``     |
"""

from __future__ import annotations

from typing import Sequence, Union

import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def axis_index(axis: AxisName):
    """This device's coordinate along a mesh axis (≙ MPI_Comm_rank)."""
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    """Devices along a mesh axis (≙ MPI_Comm_size)."""
    return lax.psum(1, axis)


def psum(x, axis: AxisName):
    """Sum-allreduce along ``axis`` (≙ MPI_Allreduce(SUM) / hvd.allreduce)."""
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    """Mean-allreduce (≙ Horovod's DistributedOptimizer gradient average)."""
    return lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    return lax.pmax(x, axis)


def pmin(x, axis: AxisName):
    return lax.pmin(x, axis)


def reduce_to_root(x, axis: AxisName):
    """Sum-reduce with the result kept only on index 0 (zeros elsewhere) —
    the π example's ``MPI_Reduce(&in, &out, 1, MPI_SUM, 0)``. XLA has no
    rooted reduce; psum + mask compiles to the same ring with a cheap
    select."""
    total = lax.psum(x, axis)
    return jnp.where(lax.axis_index(axis) == 0, total, jnp.zeros_like(total))


def broadcast_root(x, axis: AxisName):
    """Broadcast index 0's value to all (≙ MPI_Bcast; Horovod's initial
    variable broadcast). Implemented as mask + psum: only root contributes."""
    contrib = jnp.where(lax.axis_index(axis) == 0, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def all_gather(x, axis: AxisName, *, gather_axis: int = 0, tiled: bool = False):
    """Concatenate every device's shard along ``gather_axis``
    (≙ MPI_Allgather)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0):
    """Sum-reduce then scatter shards (≙ MPI_Reduce_scatter). The
    bandwidth-optimal half of a ring allreduce; XLA emits it directly."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_shift(x, axis: AxisName, *, shift: int = 1):
    """Rotate shards around the ring: device i's block moves to device
    (i+shift) mod N. The building block of ring attention and pipeline
    hand-off; lowers to a single ICI ppermute (neighbour hop when
    |shift|=1)."""
    n = axis_size_static(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    """Transpose shard ownership (≙ MPI_Alltoall): split local data along
    ``split_axis`` into N pieces, send piece j to device j, concatenate
    received pieces along ``concat_axis``. MoE token dispatch and
    DeepSpeed-Ulysses-style head↔sequence reshard use exactly this."""
    return lax.all_to_all(
        x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def axis_size_static(axis: AxisName) -> int:
    """Static size of a mesh axis (a Python int even at trace time — psum of
    a Python constant folds to the axis size; needed for building ppermute
    tables, which require concrete ints)."""
    return int(lax.psum(1, axis))
