"""Ring attention: exact attention over sequence shards via ICI ppermute.

The long-context capability (SURVEY.md §5.7 — absent from the reference,
required here). Sequence length T is sharded over the ``sequence`` mesh axis:
each device holds a [B, T/N, H, D] slice of Q, K, V. K/V blocks rotate around
the ring (one neighbour ``ppermute`` hop per step — bandwidth-optimal on an
ICI torus), and each device folds every visiting block into its local queries
with the online-softmax recurrence, so the full [T, T] score matrix is never
materialized and memory stays O(T/N · block).

This is the Liu et al. ring-attention scheme expressed as plain shard_map +
lax.scan: XLA overlaps each step's einsums with the next block's ppermute.
Causal jobs mask per-block: a visiting block strictly newer than the local
queries contributes nothing, same-index blocks get the triangular mask, older
blocks attend fully.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from mpi_operator_tpu.jaxcompat import shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_operator_tpu.runtime.topology import AXIS_SEQ

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free
                  # for fully-masked blocks

# longest sequence for which the dense fallback may materialize [T, T]
# scores; past this the chunked lowering (kernels/flash_attention.py) is the
# only memory-sane non-ring path
DENSE_FALLBACK_MAX_T = 1024


def _scores(q, k, scale):
    """Attention scores with GQA grouping: q [B,Tq,H,D], k [B,Tk,Hkv,D] with
    H = Hkv·G (consecutive q heads share a kv head) → [B,H,Tq,Tk]. K/V are
    never expanded to H heads — the grouped einsum keeps K/V bytes at Hkv
    through the ring (4x less ICI traffic at Llama-3-8B's 32/8 ratio)."""
    b, t_q, h, d = q.shape
    h_kv = k.shape[2]
    g = h // h_kv
    q5 = q.reshape(b, t_q, h_kv, g, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k, preferred_element_type=jnp.float32)
    return s.reshape(b, h, t_q, k.shape[1]) * scale


def _weighted_v(p, v):
    """p [B,H,Tq,Tk] × v [B,Tk,Hkv,D] → [B,Tq,H,D] (grouped, see _scores)."""
    b, h, t_q, t_k = p.shape
    h_kv = v.shape[2]
    g = h // h_kv
    p5 = p.reshape(b, h_kv, g, t_q, t_k)
    pv = jnp.einsum("bhgqk,bkhd->bqhgd", p5, v.astype(p.dtype))
    return pv.reshape(b, t_q, h, v.shape[3])


def _block(q, k, v, bias, carry, scale):
    """Fold one K/V block into the online-softmax accumulator.

    carry = (o, m, l): o [B,Tq,H,D] unnormalized output, m [B,H,Tq] running
    max, l [B,H,Tq] running denominator.
    """
    o, m, l = carry
    s = _scores(q, k, scale)
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = _weighted_v(p, v)
    o_new = o * jnp.transpose(corr, (0, 2, 1))[..., None] + pv
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-shard body (runs under shard_map). q,k,v: [B, T_local, H, D]."""
    from mpi_operator_tpu.parallel import collectives as c

    n = c.axis_size_static(axis_name)
    my_idx = lax.axis_index(axis_name)
    t_q, t_k = q.shape[1], k.shape[1]

    q32 = q.astype(jnp.float32)
    # Derive the accumulators from q so they inherit its varying-manual-axes
    # type (a plain jnp.zeros would be device-invariant and rejected as a
    # scan carry under shard_map).
    o0 = jnp.zeros_like(q32)
    m0 = jnp.transpose(q32[..., 0], (0, 2, 1)) * 0 + _NEG_INF
    l0 = jnp.zeros_like(m0)

    def bias_for(step_idx):
        if not causal:
            return None
        # After s hops, the resident block originated at (my_idx - s) mod n.
        # Future block: fully masked. Same block: triangular. Past: open.
        src = (my_idx - step_idx) % n
        q_pos = my_idx * t_q + jnp.arange(t_q)[:, None]
        k_pos = src * t_k + jnp.arange(t_k)[None, :]
        return jnp.where(q_pos >= k_pos, 0.0, _NEG_INF)[None, None]

    # Shift-then-consume: the resident block is folded first, then steps
    # 1..n-1 each hop K/V one neighbour and fold — no dead hop on the last
    # block (the rotation is left incomplete on purpose; K/V are consumed).
    acc0 = _block(q32, k, v, bias_for(0), (o0, m0, l0), scale)

    def step(carry, step_idx):
        o, m, l, k_blk, v_blk = carry
        k_blk = c.ring_shift(k_blk, axis_name, shift=1)
        v_blk = c.ring_shift(v_blk, axis_name, shift=1)
        o, m, l = _block(q32, k_blk, v_blk, bias_for(step_idx), (o, m, l), scale)
        return (o, m, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = lax.scan(
        step, (*acc0, k, v), jnp.arange(1, n), length=n - 1
    )
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_SEQ,
    causal: bool = True,
    scale: Optional[float] = None,
    batch_spec: P = P(("data", "fsdp")),
    head_axis: Optional[str] = "tensor",
):
    """Exact multi-head attention with the sequence dim sharded over
    ``axis_name``. Shapes are the *global* q [B,T,H,D], k/v [B,T,Hkv,D] with
    H a multiple of Hkv (GQA; consecutive q heads share a kv head — pass
    Hkv=H for plain MHA). Sharding is handled internally via shard_map; K/V
    stay at Hkv heads through the ring.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    h_part = head_axis if head_axis in mesh.axis_names else None
    seq_part = axis_name if axis_name in mesh.axis_names else None
    b_axes = batch_spec[0] if len(batch_spec) else None
    if isinstance(b_axes, str):
        b_axes = (b_axes,)
    b_part = tuple(a for a in (b_axes or ()) if a in mesh.axis_names) or None
    spec = P(b_part, seq_part, h_part, None)
    fn = functools.partial(
        _ring_attention_local,
        axis_name=axis_name,
        causal=causal,
        scale=scale,
    )
    if seq_part is None:
        # No sequence axis in this mesh: single-shard attention, no ring.
        # Above the threshold the dense [T,T] score matrix is a production
        # OOM (8B-class sequence lengths), so route to the memory-bounded
        # chunked lowering; dense stays the small-case/test oracle.
        if q.shape[1] > DENSE_FALLBACK_MAX_T:
            from mpi_operator_tpu.kernels.flash_attention import (
                chunked_reference,
            )

            return chunked_reference(q, k, v, causal=causal, scale=scale)
        return dense_attention(q, k, v, causal=causal, scale=scale)
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def dense_attention(q, k, v, *, causal: bool, scale: float):
    """Reference (and no-sequence-axis fallback) attention; also the oracle
    the tests compare ring attention against. GQA-aware like the ring path."""
    s = _scores(q, k, scale)
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return _weighted_v(p, v).astype(q.dtype)
