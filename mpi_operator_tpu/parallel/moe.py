"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` axis.

Absent from the reference (SURVEY.md §2.5) — supplied here as the EP
capability. TPU-native switch-routing design:

- top-1 (switch) router with capacity factor and jitter-free softmax
  probabilities; dropped tokens pass through the residual (standard switch
  semantics);
- experts sharded over the ``expert`` mesh axis; the scatter into per-expert
  capacity buffers is the dispatch, and XLA derives the token movement (the
  all-to-all-shaped reshard, ≙ MPI_Alltoall) from the buffer's expert-axis
  sharding;
- everything static-shaped (capacity buffers) so XLA compiles one program —
  no data-dependent shapes.

Batch/token dims stay sharded over (data, fsdp) as usual; the all_to_all
reshards tokens expert-major only inside this layer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from mpi_operator_tpu.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi_operator_tpu.runtime.topology import AXIS_EXPERT

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 64
    d_ff: int = 256
    n_experts: int = 8
    capacity_factor: float = 1.25
    compute_dtype: Any = jnp.bfloat16


def init(config: MoEConfig, key) -> Params:
    kr, k1, k2 = jax.random.split(key, 3)
    s_d = config.d_model**-0.5
    s_f = config.d_ff**-0.5
    e = config.n_experts
    return {
        "router": {"w": jax.random.normal(kr, (config.d_model, e), jnp.float32) * s_d},
        "w_in": {
            "w": jax.random.normal(k1, (e, config.d_model, config.d_ff), jnp.float32) * s_d
        },
        "w_out": {
            "w": jax.random.normal(k2, (e, config.d_ff, config.d_model), jnp.float32) * s_f
        },
    }


def logical_axes(config: MoEConfig) -> Params:
    return {
        "router": {"w": ("embed", None)},
        "w_in": {"w": ("expert", "embed", "mlp")},
        "w_out": {"w": ("expert", "mlp", "embed")},
    }


def _route(logits, n_experts, capacity):
    """Top-1 routing with capacity. Returns (expert_idx, slot_idx, keep_mask,
    gate) per token; slot via a cumulative count per expert."""
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [T, E]
    position = jnp.cumsum(onehot, axis=0) * onehot  # 1-based slot per token
    slot = jnp.max(position, axis=-1) - 1  # [T]
    keep = slot < capacity
    return expert_idx, slot, keep, gate, probs


def aux_load_balance_loss(probs, expert_idx, n_experts):
    """Switch-transformer load-balancing loss: E * Σ_e f_e · P_e."""
    me = jnp.mean(jax.nn.one_hot(expert_idx, n_experts, dtype=probs.dtype), axis=0)
    pe = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(me * pe)


def apply(config: MoEConfig, params: Params, x, *, mesh: Mesh = None):
    """x [B, T, D] → (y [B, T, D], aux_loss scalar).

    With a mesh carrying an ``expert`` axis the expert FFNs run sharded and
    tokens move via all_to_all; otherwise all experts run locally (same
    math, zero collectives) — one code path for tests and deployment."""
    b, t, d = x.shape
    e = config.n_experts
    tokens = x.reshape(b * t, d)
    n_tok = b * t
    capacity = int(config.capacity_factor * n_tok / e)
    capacity = max(capacity, 1)

    logits = tokens.astype(jnp.float32) @ params["router"]["w"]
    expert_idx, slot, keep, gate, probs = _route(logits, e, capacity)
    aux = aux_load_balance_loss(probs, expert_idx, e)

    # scatter tokens into [E, C, D] capacity buffers (dropped → zeros)
    buf = jnp.zeros((e, capacity, d), tokens.dtype)
    safe_slot = jnp.where(keep, slot, 0)
    buf = buf.at[expert_idx, safe_slot].add(
        jnp.where(keep[:, None], tokens, 0.0)
    )

    dt = config.compute_dtype

    def expert_ffn(w_in, w_out, xb):
        h = jax.nn.gelu(xb.astype(dt) @ w_in.astype(dt))
        return (h @ w_out.astype(dt)).astype(xb.dtype)

    if mesh is not None and AXIS_EXPERT in mesh.axis_names and mesh.shape[AXIS_EXPERT] > 1:

        def sharded(buf_local, w_in_local, w_out_local):
            # buf arrives sharded on dim 0: each device holds its experts'
            # capacity buffers (XLA inserted the dispatch reshard). Run them.
            def one(xb, wi, wo):
                return expert_ffn(wi, wo, xb)

            return jax.vmap(one)(buf_local, w_in_local, w_out_local)

        out_buf = shard_map(
            sharded,
            mesh=mesh,
            in_specs=(P(AXIS_EXPERT), P(AXIS_EXPERT), P(AXIS_EXPERT)),
            out_specs=P(AXIS_EXPERT),
        )(buf, params["w_in"]["w"], params["w_out"]["w"])
    else:
        out_buf = jax.vmap(lambda xb, wi, wo: expert_ffn(wi, wo, xb))(
            buf, params["w_in"]["w"], params["w_out"]["w"]
        )

    # gather back: token i reads its (expert, slot) result, scaled by gate
    gathered = out_buf[expert_idx, safe_slot]
    y = jnp.where(keep[:, None], gathered * gate[:, None].astype(gathered.dtype), 0.0)
    return y.reshape(b, t, d), aux
