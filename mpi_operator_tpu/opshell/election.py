"""Leader election over the object store.

≙ the Endpoints-lock leader election of the reference
(v2/cmd/mpi-operator/app/server.go:62-64, 210-257: 15s lease, 10s renew
deadline, 5s retry; OnStartedLeading runs the controller, losing the lease
is fatal). Same state machine here, with the lock record kept in the
ObjectStore (the framework's apiserver equivalent) as a ConfigMap-shaped
object — multiple operator replicas sharing a store (or, later, a replicated
store backend) elect exactly one active reconciler.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from mpi_operator_tpu.machinery.objects import ConfigMap
from mpi_operator_tpu.machinery.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)
from mpi_operator_tpu.opshell import metrics

log = logging.getLogger("tpujob.election")

LOCK_NAME = "tpu-operator-leader-lock"
KEY_HOLDER = "holderIdentity"
KEY_RENEW = "renewTime"


@dataclass
class ElectionConfig:
    lease_duration: float = 15.0  # ≙ server.go:62 leaseDuration
    renew_deadline: float = 10.0  # ≙ renewDeadline
    retry_period: float = 5.0     # ≙ retryPeriod
    namespace: str = "kube-system"


class LeaderElector:
    """run() blocks: acquires (or waits for) the lease, calls on_started in a
    thread, keeps renewing; calls on_stopped and returns if the lease is
    lost. identity defaults to a uuid (≙ hostname+uuid, server.go:219)."""

    def __init__(
        self,
        store: ObjectStore,
        *,
        identity: Optional[str] = None,
        config: Optional[ElectionConfig] = None,
        on_started: Callable[[], None],
        on_stopped: Callable[[], None],
    ):
        self.store = store
        self.identity = identity or str(uuid.uuid4())
        self.config = config or ElectionConfig()
        self.on_started = on_started
        self.on_stopped = on_stopped
        self._stop = threading.Event()
        self.is_leader = False

    # -- lock record -------------------------------------------------------

    def _read(self) -> Optional[ConfigMap]:
        try:
            return self.store.get("ConfigMap", self.config.namespace, LOCK_NAME)
        except NotFound:
            return None

    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        try:
            cur = self._read()
        except Exception:
            # a transient store error (e.g. sqlite contention under load)
            # is a failed ATTEMPT, not a dead elector — the renew_deadline
            # window absorbs it
            log.warning("lease read failed; retrying", exc_info=True)
            return False
        if cur is None:
            cm = ConfigMap()
            cm.metadata.name = LOCK_NAME
            cm.metadata.namespace = self.config.namespace
            cm.data = {KEY_HOLDER: self.identity, KEY_RENEW: str(now)}
            try:
                self.store.create(cm)
                return True
            except AlreadyExists:
                return False
            except Exception:
                log.warning("lease create failed; retrying", exc_info=True)
                return False
        holder = cur.data.get(KEY_HOLDER, "")
        renew = float(cur.data.get(KEY_RENEW, "0"))
        if holder != self.identity and now - renew < self.config.lease_duration:
            return False  # someone else holds a live lease
        cur.data[KEY_HOLDER] = self.identity
        cur.data[KEY_RENEW] = str(now)
        try:
            self.store.update(cur)  # optimistic: resource_version guards races
            return True
        except (Conflict, NotFound):
            return False
        except Exception:
            log.warning("lease renew failed; retrying", exc_info=True)
            return False

    # -- loop --------------------------------------------------------------

    def run(self) -> None:
        cfg = self.config
        # acquire
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            self._stop.wait(cfg.retry_period)
        if self._stop.is_set():
            return
        self.is_leader = True
        metrics.is_leader.set(1)
        worker = threading.Thread(target=self.on_started, daemon=True)
        worker.start()
        # renew
        last_renew = time.time()
        while not self._stop.is_set():
            self._stop.wait(cfg.retry_period)
            if self._stop.is_set():
                break
            if self._try_acquire_or_renew():
                last_renew = time.time()
            elif time.time() - last_renew > cfg.renew_deadline:
                # ≙ OnStoppedLeading → klog.Fatalf: this is fatal for every
                # pod this replica executes — it must never be silent
                log.warning(
                    "leader lease lost (no successful renew for %.1fs); "
                    "stopping all components", time.time() - last_renew,
                )
                break
        self.is_leader = False
        metrics.is_leader.set(0)
        self.on_stopped()

    def stop(self) -> None:
        self._stop.set()

    def release(self) -> None:
        """Drop the lock record if we hold it (graceful shutdown)."""
        cur = self._read()
        if cur is not None and cur.data.get(KEY_HOLDER) == self.identity:
            self.store.try_delete("ConfigMap", self.config.namespace, LOCK_NAME)
