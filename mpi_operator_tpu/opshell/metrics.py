"""Prometheus-style metrics registry.

≙ the promauto counters of the reference
(v2/pkg/controller/mpi_job_controller.go:119-135 —
mpi_operator_jobs_created_total / _successful_total / _failed_total /
mpi_operator_job_info — and mpi_operator_is_leader,
v2/cmd/mpi-operator/app/server.go:73-78). Same metric names with the
``tpu_operator_`` prefix; rendered in Prometheus text exposition format by
``render()`` for the /metrics endpoint (opshell.server).
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind  # counter | gauge
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for k, v in sorted(self._values.items()):
                if k:
                    lbl = "{" + ",".join(f'{a}="{b}"' for a, b in k) + "}"
                else:
                    lbl = ""
                lines.append(f"{self.name}{lbl} {v:g}")
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str) -> _Metric:
        return self._register(name, help_, "counter")

    def gauge(self, name: str, help_: str) -> _Metric:
        return self._register(name, help_, "gauge")

    def _register(self, name: str, help_: str, kind: str) -> _Metric:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = _Metric(name, help_, kind)
            return self._metrics[name]

    def render(self) -> str:
        with self._lock:
            return "\n".join(m.render() for m in self._metrics.values()) + "\n"


REGISTRY = Registry()

jobs_created = REGISTRY.counter(
    "tpu_operator_jobs_created_total", "Counts number of TPU jobs created"
)
jobs_successful = REGISTRY.counter(
    "tpu_operator_jobs_successful_total", "Counts number of TPU jobs successful"
)
jobs_failed = REGISTRY.counter(
    "tpu_operator_jobs_failed_total", "Counts number of TPU jobs failed"
)
jobs_restarted = REGISTRY.counter(
    "tpu_operator_jobs_restarted_total", "Counts number of TPU job restarts"
)
gang_restarts = REGISTRY.counter(
    "tpu_operator_gang_restarts_total",
    "Counts executed gang restart generations (whole-gang teardown + "
    "relaunch), INCLUDING free preemption restarts that do not burn "
    "backoffLimit — the restart-storm signal: a single injected failure "
    "must move this by exactly one",
)
job_info = REGISTRY.gauge(
    "tpu_operator_job_info", "Info about a TPU job (coordinator pod, namespace)"
)
is_leader = REGISTRY.gauge(
    "tpu_operator_is_leader", "1 when this replica holds the leader lease"
)
nodes_lost = REGISTRY.counter(
    "tpu_operator_nodes_lost_total",
    "Counts nodes whose agent stopped heartbeating past the grace window",
)
pods_evicted = REGISTRY.counter(
    "tpu_operator_pods_evicted_total",
    "Counts pods the node monitor evicted off nodes that stopped "
    "heartbeating (ctl drain evictions happen client-side and are not "
    "counted here)",
)
gangs_preempted = REGISTRY.counter(
    "tpu_operator_gangs_preempted_total",
    "Counts running gangs evicted whole to make room for a "
    "higher-priority pending gang (--preemption-grace)",
)
informer_synced = REGISTRY.gauge(
    "tpu_operator_informer_synced",
    "1 once the informer cache holds its initial snapshot (reconcilers "
    "gate on this, like WaitForCacheSync); 0 while cold, absent when "
    "running with --no-informer-cache",
)
informer_objects = REGISTRY.gauge(
    "tpu_operator_informer_objects",
    "Objects held per kind by the informer cache (the lister working set)",
)
store_write_requests = REGISTRY.counter(
    "tpu_operator_store_write_requests_total",
    "Store-server writes by verb: create/update/delete/patch are "
    "requests, patch_batch is one batched request and patch_item its "
    "per-object applications — the patch-vs-update split shows how much "
    "of the write path rides the single-round-trip merge-patch verb",
)
store_write_conflicts = REGISTRY.counter(
    "tpu_operator_store_write_conflicts_total",
    "Optimistic-concurrency conflicts (409) the store server bounced — "
    "each one was a wasted write round-trip plus a client re-read; the "
    "merge-patch write path exists to drive this to ~zero",
)
store_replication_lag = REGISTRY.gauge(
    "tpu_operator_store_replication_lag_entries",
    "Per-follower replication lag in log entries (leader head rv minus "
    "the follower's applied rv, labeled by follower) — 0 on a healthy "
    "set since the leader ships synchronously; a persistently lagging "
    "follower is one partition away from a lossy quorum",
)
store_replication_failovers = REGISTRY.counter(
    "tpu_operator_store_replication_failovers_total",
    "Counts won replica-set elections (lease takeovers). Steady state "
    "is exactly 1 (the initial election); every increment after that is "
    "a leader loss the runbook's 'leader loss' row explains",
)
store_writes_elided = REGISTRY.counter(
    "tpu_operator_store_writes_elided_total",
    "Writes skipped because the intended object matched the lister's copy "
    "(no-op write elision, by component) — the write-side twin of the "
    "informer cache's zero-read guarantee",
)
