"""Prometheus-style metrics registry.

≙ the promauto counters of the reference
(v2/pkg/controller/mpi_job_controller.go:119-135 —
mpi_operator_jobs_created_total / _successful_total / _failed_total /
mpi_operator_job_info — and mpi_operator_is_leader,
v2/cmd/mpi-operator/app/server.go:73-78). Same metric names with the
``tpu_operator_`` prefix; rendered in Prometheus text exposition format by
``render()`` for the /metrics endpoint (opshell.server).

Three kinds: counter, gauge, and — since the tracing round (ISSUE 9) —
**histogram**, exported in the standard ``_bucket``/``_sum``/``_count``
form with cumulative ``le`` buckets. Histogram instruments are wired at
the span-close sites of machinery/trace.py's consumers (reconcile, store
request, watch delivery, scheduler bind, replication ship, failover), so
the latencies PERF.md claims are the latencies /metrics exports —
``bench_controlplane.py``'s hist mode reads its p50/p99 back OUT of the
exposition via :func:`parse_exposition` + :func:`histogram_quantile` to
prove the two agree.

Label values are escaped per the exposition spec (``\\`` → ``\\\\``,
``"`` → ``\\"``, newline → ``\\n``); HELP text escapes ``\\`` and
newlines. :func:`parse_exposition` is the STRICT round-trip parser the
test suite (and the verify static gate) runs over the full registry so
the endpoint stays machine-valid forever.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def escape_label_value(value: str) -> str:
    """Exposition-format label-value escaping: backslash, double-quote and
    newline are the three characters the spec requires escaping — emitting
    them raw produces text a strict scraper rejects (the bug this round's
    satellite fixed: a node name with a quote broke the whole endpoint)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline (quotes are legal there)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(pairs: Sequence[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    return f"{v:g}"


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind  # counter | gauge
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted(labels.items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop one labeled series (a per-object gauge whose object was
        deleted must stop exporting its last value forever — and a churn
        of uniquely-named objects must not grow the registry unboundedly)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            # an empty COUNTER family renders the idiomatic zero; an
            # empty GAUGE family renders NO sample — a per-entity gauge
            # (job_goodput_ratio) with no entities is absent, and a
            # synthesized 0 reads as a real entity at its worst value
            # (the SLO monitor would page goodput-collapse on a fleet
            # with no stepping jobs — the soak bench caught exactly this)
            if not self._values and self.kind == "counter":
                lines.append(f"{self.name} 0")
            for k, v in sorted(self._values.items()):
                lines.append(f"{self.name}{_render_labels(k)} {v:g}")
        return "\n".join(lines)


# latency buckets (seconds): sub-ms store hits through multi-second
# failovers — chosen so the write-path p50s PERF.md records (~1-10ms) land
# mid-range with neighbors close enough for quantile estimates to agree
# with the bench's direct timers within one bucket step
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Histogram:
    """Prometheus histogram: cumulative ``le`` buckets + ``_sum`` +
    ``_count`` per label set. ``observe`` is the one write verb — wired at
    the span-close sites so tracing and metrics can never disagree about
    what was measured."""

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.name = name
        self.help = help_
        self.kind = "histogram"
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"histogram {name}: needs at least one bucket")
        self.buckets: Tuple[float, ...] = tuple(bs)
        # label-set key → [bucket counts..., +Inf count] ; (sum, count)
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted(labels.items()))

    def observe(self, value: float, **labels: str) -> None:
        if "le" in labels:
            raise ValueError("'le' is the reserved histogram bucket label")
        i = bisect.bisect_left(self.buckets, value)
        k = self._key(labels)
        with self._lock:
            counts = self._counts.get(k)
            if counts is None:
                counts = self._counts[k] = [0] * (len(self.buckets) + 1)
                self._sums[k] = 0.0
            counts[i] += 1
            self._sums[k] += value

    def count(self, **labels: str) -> int:
        with self._lock:
            counts = self._counts.get(self._key(labels))
            return sum(counts) if counts else 0

    def snapshot(self, **labels: str) -> List[Tuple[float, int]]:
        """Cumulative (le, count) pairs incl. +Inf — quantile input."""
        with self._lock:
            counts = self._counts.get(self._key(labels))
            if counts is None:
                return []
        out = []
        acc = 0
        for le, c in zip((*self.buckets, math.inf), counts):
            acc += c
            out.append((le, acc))
        return out

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(
                (k, list(c), self._sums[k]) for k, c in self._counts.items()
            )
        for k, counts, total in items:
            acc = 0
            for le, c in zip((*self.buckets, math.inf), counts):
                acc += c
                pairs = (*k, ("le", _fmt(le)))
                lines.append(f"{self.name}_bucket{_render_labels(pairs)} {acc}")
            lines.append(f"{self.name}_sum{_render_labels(k)} {total:g}")
            lines.append(f"{self.name}_count{_render_labels(k)} {acc}")
        return "\n".join(lines)


def histogram_quantile(q: float,
                       cumulative: Sequence[Tuple[float, int]]) -> float:
    """Estimate the q-quantile from cumulative (le, count) pairs, the way
    PromQL's histogram_quantile does: find the bucket the rank lands in and
    interpolate linearly inside it (the +Inf bucket clamps to the highest
    finite bound). Resolution is therefore one bucket step — exactly the
    agreement tolerance the hist bench mode asserts."""
    if not cumulative:
        return 0.0
    total = cumulative[-1][1]
    if total <= 0:
        return 0.0
    rank = q * total
    prev_le, prev_n = 0.0, 0
    for le, n in cumulative:
        if n >= rank:
            if le == math.inf:
                return prev_le  # clamp, like PromQL
            if n == prev_n:
                return le
            return prev_le + (le - prev_le) * (rank - prev_n) / (n - prev_n)
        prev_le, prev_n = le, n
    return prev_le


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str) -> _Metric:
        return self._register(name, help_, "counter")

    def gauge(self, name: str, help_: str) -> _Metric:
        return self._register(name, help_, "gauge")

    def histogram(
        self, name: str, help_: str,
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> _Histogram:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = _Histogram(name, help_, buckets)
            m = self._metrics[name]
        if not isinstance(m, _Histogram):
            raise ValueError(f"{name} is already registered as {m.kind}")
        return m

    def _register(self, name: str, help_: str, kind: str) -> _Metric:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = _Metric(name, help_, kind)
            return self._metrics[name]

    def names(self) -> List[str]:
        """Every registered family name — the catalog SLO configs are
        validated against (an objective naming an unknown family fails
        closed at load; oplint OBS003 catches it at diff time)."""
        with self._lock:
            return sorted(self._metrics)

    def kind_of(self, name: str) -> Optional[str]:
        """'counter' | 'gauge' | 'histogram' for a registered family,
        None for unknown — SLO config validation matches objective kinds
        against instrument kinds (a latency objective on a counter is a
        config bug, not a runtime surprise)."""
        with self._lock:
            m = self._metrics.get(name)
        return getattr(m, "kind", None)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"


# ---------------------------------------------------------------------------
# strict exposition parser (round-trip gate + the hist bench's read path)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)(?: (?P<ts>-?\d+))?$"
)
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"'
)
_ESCAPE_RE = re.compile(r"\\(.)")


class ExpositionError(ValueError):
    """A line the text exposition format forbids — the strict parser's
    one failure mode, so tests fail loudly the moment render() drifts."""


def _unescape_label(value: str) -> str:
    def sub(m) -> str:
        c = m.group(1)
        if c == "n":
            return "\n"
        if c in ('"', "\\"):
            return c
        raise ExpositionError(f"invalid escape \\{c} in label value")

    return _ESCAPE_RE.sub(sub, value)


def _parse_labels(body: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    pos = 0
    while pos < len(body):
        m = _LABEL_RE.match(body, pos)
        if not m:
            raise ExpositionError(f"malformed label pair at {body[pos:]!r}")
        out[m.group("key")] = _unescape_label(m.group("value"))
        pos = m.end()
        if pos < len(body):
            if body[pos] != ",":
                raise ExpositionError(
                    f"expected ',' between labels at {body[pos:]!r}"
                )
            pos += 1
    return out


def parse_exposition(text: str) -> Dict[str, Dict[str, object]]:
    """STRICT parse of Prometheus text format. Returns
    ``{family: {"help": str, "type": str, "samples": [(name, labels, value)]}}``
    and raises :class:`ExpositionError` on anything malformed — unescaped
    quotes/newlines in label values, bad sample lines, samples outside a
    TYPE'd family, non-float values. The full-registry round-trip test and
    the verify static gate run this over ``render()`` output."""
    families: Dict[str, Dict[str, object]] = {}
    current: Optional[str] = None
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ExpositionError(f"bad metric name in HELP: {name!r}")
            families.setdefault(
                name, {"help": "", "type": "untyped", "samples": []}
            )["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ExpositionError(f"bad metric name in TYPE: {name!r}")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ExpositionError(f"unknown TYPE {kind!r} for {name}")
            families.setdefault(
                name, {"help": "", "type": "untyped", "samples": []}
            )["type"] = kind
            current = name
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ExpositionError(f"malformed sample line: {line!r}")
        name = m.group("name")
        labels = _parse_labels(m.group("labels") or "")
        sval = m.group("value")
        if sval == "+Inf":
            value = math.inf
        elif sval == "-Inf":
            value = -math.inf
        else:
            try:
                value = float(sval)
            except ValueError:
                raise ExpositionError(
                    f"non-numeric sample value {sval!r} in {line!r}"
                ) from None
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and base in families \
                    and families[base]["type"] == "histogram":
                family = base
                break
        if family not in families:
            raise ExpositionError(
                f"sample {name!r} outside any HELP/TYPE family"
            )
        if current is not None and family != current and name != current:
            # interleaved families are illegal in the text format
            raise ExpositionError(
                f"sample {name!r} interleaved into family {current!r}"
            )
        families[family]["samples"].append((name, labels, value))
    return families


def exposition_quantile(
    text: str, family: str, q: float, **labels: str
) -> float:
    """Read a histogram quantile straight out of exposition text (the hist
    bench mode's read path: what a real Prometheus would compute)."""
    fams = parse_exposition(text)
    if family not in fams:
        raise KeyError(f"no histogram family {family!r} in exposition")
    pairs: List[Tuple[float, int]] = []
    for name, lbls, value in fams[family]["samples"]:
        if not name.endswith("_bucket"):
            continue
        rest = {k: v for k, v in lbls.items() if k != "le"}
        if rest != labels:
            continue
        le = lbls.get("le", "")
        pairs.append((math.inf if le == "+Inf" else float(le), int(value)))
    pairs.sort()
    return histogram_quantile(q, pairs)


REGISTRY = Registry()

jobs_created = REGISTRY.counter(
    "tpu_operator_jobs_created_total", "Counts number of TPU jobs created"
)
jobs_successful = REGISTRY.counter(
    "tpu_operator_jobs_successful_total", "Counts number of TPU jobs successful"
)
jobs_failed = REGISTRY.counter(
    "tpu_operator_jobs_failed_total", "Counts number of TPU jobs failed"
)
jobs_restarted = REGISTRY.counter(
    "tpu_operator_jobs_restarted_total", "Counts number of TPU job restarts"
)
gang_restarts = REGISTRY.counter(
    "tpu_operator_gang_restarts_total",
    "Counts executed gang restart generations (whole-gang teardown + "
    "relaunch), INCLUDING free preemption restarts that do not burn "
    "backoffLimit — the restart-storm signal: a single injected failure "
    "must move this by exactly one",
)
job_info = REGISTRY.gauge(
    "tpu_operator_job_info", "Info about a TPU job (coordinator pod, namespace)"
)
is_leader = REGISTRY.gauge(
    "tpu_operator_is_leader", "1 when this replica holds the leader lease"
)
nodes_lost = REGISTRY.counter(
    "tpu_operator_nodes_lost_total",
    "Counts nodes whose agent stopped heartbeating past the grace window",
)
pods_evicted = REGISTRY.counter(
    "tpu_operator_pods_evicted_total",
    "Counts pods the node monitor evicted off nodes that stopped "
    "heartbeating (ctl drain evictions happen client-side and are not "
    "counted here)",
)
gangs_preempted = REGISTRY.counter(
    "tpu_operator_gangs_preempted_total",
    "Counts running gangs evicted whole to make room for a "
    "higher-priority pending gang (--preemption-grace)",
)
drains_total = REGISTRY.counter(
    "tpu_operator_drains_total",
    "Disruption-plane drain lifecycle events by outcome= label: started "
    "(maintenance notice adopted), gang_migrated (a batch gang "
    "checkpoint-then-migrated off the node), completed (node empty), "
    "escalated (deadline/dead-node hard eviction fired)",
)
drain_budget_blocked = REGISTRY.gauge(
    "tpu_operator_drain_budget_blocked",
    "Serves currently PARKING a node drain because retiring their doomed "
    "replica would drop ready_total below the DisruptionBudget (cluster "
    "too full to surge a replacement); 0 when every drain can proceed — "
    "a sustained nonzero means capacity must free or the maintenance "
    "deadline will hard-evict",
)
informer_synced = REGISTRY.gauge(
    "tpu_operator_informer_synced",
    "1 once the informer cache holds its initial snapshot (reconcilers "
    "gate on this, like WaitForCacheSync); 0 while cold, absent when "
    "running with --no-informer-cache",
)
informer_objects = REGISTRY.gauge(
    "tpu_operator_informer_objects",
    "Objects held per kind by the informer cache (the lister working set)",
)
store_write_requests = REGISTRY.counter(
    "tpu_operator_store_write_requests_total",
    "Store-server writes by verb: create/update/delete/patch are "
    "requests, patch_batch is one batched request and patch_item its "
    "per-object applications — the patch-vs-update split shows how much "
    "of the write path rides the single-round-trip merge-patch verb",
)
store_write_conflicts = REGISTRY.counter(
    "tpu_operator_store_write_conflicts_total",
    "Optimistic-concurrency conflicts (409) the store server bounced — "
    "each one was a wasted write round-trip plus a client re-read; the "
    "merge-patch write path exists to drive this to ~zero",
)
store_replication_lag = REGISTRY.gauge(
    "tpu_operator_store_replication_lag_entries",
    "Per-follower replication lag in log entries (leader head rv minus "
    "the follower's applied rv, labeled by follower) — 0 on a healthy "
    "set since the leader ships synchronously; a persistently lagging "
    "follower is one partition away from a lossy quorum",
)
store_replication_failovers = REGISTRY.counter(
    "tpu_operator_store_replication_failovers_total",
    "Counts won replica-set elections (lease takeovers). Steady state "
    "is exactly 1 (the initial election); every increment after that is "
    "a leader loss the runbook's 'leader loss' row explains",
)
replication_snapshot_bytes = REGISTRY.counter(
    "tpu_operator_replication_snapshot_bytes_total",
    "Bytes pulled over chunked snapshot transfers (cold follower joins, "
    "divergent-suffix resyncs) — steady state is FLAT; a climbing rate "
    "means some follower keeps falling off the log-retention window and "
    "resyncing (see the runbook's 'snapshot transfer stuck' row)",
)
store_writes_elided = REGISTRY.counter(
    "tpu_operator_store_writes_elided_total",
    "Writes skipped because the intended object matched the lister's copy "
    "(no-op write elision, by component) — the write-side twin of the "
    "informer cache's zero-read guarantee",
)
store_tenant_queued = REGISTRY.counter(
    "tpu_operator_store_tenant_queued_total",
    "Requests that had to WAIT for a fair-queue seat, by tenant "
    "(machinery/fairqueue.py) — a persistently queued tenant is either "
    "noisy (expected: its own load) or starved (check the noisy "
    "neighbor's rejected counter and the per-tenant queue snapshot)",
)
store_tenant_rejected = REGISTRY.counter(
    "tpu_operator_store_tenant_rejected_total",
    "Requests load-shed with 429 TooManyRequests by tenant and reason "
    "(rate = over its token bucket, queue-full = bounded wait queue "
    "overflow, timeout = waited max_wait without a seat) — nonzero for "
    "a noisy tenant is the fair queue WORKING, nonzero for everyone is "
    "an undersized max_inflight",
)
events_pruned = REGISTRY.counter(
    "tpu_operator_events_pruned_total",
    "Events deleted by the controller's TTL sweep (kube prunes its events "
    "the same way; without this the store grows without bound)",
)

# --- the serving workload class (ISSUE 11) ---------------------------------

serve_scale_events = REGISTRY.counter(
    "tpu_operator_serve_scale_events_total",
    "Autoscaler replica-count changes by direction (up/down) — a high "
    "rate with alternating directions is flapping the stabilization "
    "windows should be absorbing (widen scale_down_stabilization_s)",
)
serve_desired_replicas = REGISTRY.gauge(
    "tpu_operator_serve_desired_replicas",
    "The autoscaler's latest replica target per serve (labeled "
    "serve=<ns>/<name>) — compare against ready replicas in `ctl serve "
    "status` to see convergence",
)
serve_replicas_ready = REGISTRY.gauge(
    "tpu_operator_serve_replicas_ready",
    "Ready serving replicas per serve (every gang member Running AND "
    "ready) — the supply side of the autoscaler's loop",
)

# --- the workload telemetry plane (ISSUE 15) -------------------------------

job_goodput_ratio = REGISTRY.gauge(
    "tpu_operator_job_goodput_ratio",
    "Per-job goodput (labeled job=<ns>/<name>): productive step-compute "
    "seconds / wall seconds since admission, restart downtime included in "
    "the denominator — exported once the job has completed at least one "
    "step, removed at terminal/delete; the goodput-collapse burn-rate "
    "objective pages when a running job's ratio sits below its floor",
)
job_stragglers = REGISTRY.gauge(
    "tpu_operator_job_stragglers",
    "Gang members currently flagged as stragglers per job (step p50 above "
    "the gang median by the skew threshold); the Straggler Event/condition "
    "name the exact pod and node",
)
restart_to_first_step = REGISTRY.histogram(
    "tpu_operator_restart_to_first_step_seconds",
    "Gang-restart outage span: restart observed (evict/teardown) to the "
    "FIRST completed step of the relaunched generation, labeled kind= "
    "(migration for planned Maintenance moves, restart otherwise) — THE "
    "baseline ROADMAP item 5's compile-cache work must beat",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 60.0, 120.0, 300.0,
             600.0),
)
step_latency = REGISTRY.histogram(
    "tpu_operator_step_latency_seconds",
    "Per-step wall seconds attributed to each stall bucket (labeled "
    "bucket=compile|input|compute|sync|ckpt, plus bucket=step for the "
    "whole step): the aggregator observes each tick's per-step bucket "
    "averages, so the distribution shows WHERE step time goes fleet-wide",
)
goodput_sync_latency = REGISTRY.histogram(
    "tpu_operator_goodput_sync_latency_seconds",
    "Goodput-aggregator pass wall time (read every running job's worker "
    "train_stats, roll up goodput/skew, write telemetry + gauges); "
    "observed where the goodput.sync span closes",
)

# --- fleet soak & rescheduling (ISSUE 18) ----------------------------------

schedulable_contiguous_chips = REGISTRY.gauge(
    "tpu_operator_schedulable_contiguous_chips",
    "Largest free chip block on any single live schedulable node — the "
    "biggest gang MEMBER placeable right now without any move. Total free "
    "chips can be ample while this sits at 1 (fragmentation); the "
    "defragmenting rescheduler exists to raise it, and the soak bench's "
    "A/B acceptance bar is this gauge moving vs --no-rescheduler",
)
fleet_free_chips = REGISTRY.gauge(
    "tpu_operator_fleet_free_chips",
    "Total unclaimed chips across live schedulable nodes (capacity minus "
    "bound unfinished pods) — the denominator fragmentation is judged "
    "against: a queued gang that fits total-free but not contiguous-free "
    "is the rescheduler's make-room trigger and `ctl top --fragmentation`'s "
    "exit-1 condition",
)
reschedules_total = REGISTRY.counter(
    "tpu_operator_reschedules_total",
    "Rescheduler actions by outcome= (straggler_move: a gang migrated off "
    "straggler-flagged hardware; defrag_drain: a maintenance-window drain "
    "stamped on a victim node to consolidate its gangs; defrag_complete: "
    "a victim node emptied and returned to service). Every move rides the "
    "free checkpoint-then-migrate seam — this counter climbing NEVER "
    "implies burned restart budgets",
)
rescheduler_parked = REGISTRY.gauge(
    "tpu_operator_rescheduler_parked",
    "Candidate moves the rescheduler wanted this pass but parked under "
    "governance (migration window cap, hysteresis, min-gain floor, no "
    "alternative placement) — each park leaves an explaining Event; "
    "persistently nonzero alongside a low contiguous-chips gauge means "
    "the knobs are too tight for the fleet's churn ('fleet fragmented' "
    "runbook row)",
)

# --- the SLO plane (ISSUE 13): the monitor's own health + alert state ------

slo_alerts_firing = REGISTRY.gauge(
    "tpu_operator_slo_alerts_firing",
    "1 per FIRING SLO alert (labeled objective=) — the pager's source of "
    "truth; `ctl alerts` renders the same Alert objects this gauge mirrors",
)
slo_alerts_fired = REGISTRY.counter(
    "tpu_operator_slo_alerts_fired_total",
    "SLO alert firings by objective (a resolve+refire counts again) — a "
    "climbing rate on one objective is a recurring regression, not noise",
)
monitor_scrape_errors = REGISTRY.counter(
    "tpu_operator_monitor_scrape_errors_total",
    "Failed scrape attempts by instance (unreachable target, malformed "
    "exposition) — the 'monitor silent' runbook row starts here: a dead "
    "target also shows as up{instance=}==0 in the monitor's ring",
)
monitor_series_dropped = REGISTRY.gauge(
    "tpu_operator_monitor_series_dropped",
    "Distinct timeseries the scraper refused past its max_series bound "
    "(a label-cardinality explosion in a scraped target degrades SLO "
    "coverage instead of growing monitor memory without limit; the "
    "count saturates at 8x max_series)",
)

# --- the histogram catalog (ISSUE 9): latencies at the span-close sites ----

reconcile_latency = REGISTRY.histogram(
    "tpu_operator_reconcile_latency_seconds",
    "Controller sync_handler wall time per reconcile — the control "
    "plane's headline latency (PERF 'reconcile p50'); observed where the "
    "controller.reconcile span closes",
)
store_request_latency = REGISTRY.histogram(
    "tpu_operator_store_request_latency_seconds",
    "Store-server request handling time by verb and backing store class "
    "(watch long-polls excluded — they park by design); observed where "
    "the server-side store.request span closes",
)
watch_delivery_lag = REGISTRY.histogram(
    "tpu_operator_watch_delivery_lag_seconds",
    "Commit-to-informer-delivery lag per watch event (how stale a lister "
    "read can be); observed as the informer cache applies each event",
)
scheduler_bind_latency = REGISTRY.histogram(
    "tpu_operator_scheduler_bind_latency_seconds",
    "Gang-scheduler pod-binding write latency (the admission hot path); "
    "observed where the scheduler.bind span closes",
)
scheduler_sync_latency = REGISTRY.histogram(
    "tpu_operator_scheduler_sync_latency_seconds",
    "Gang-scheduler full admission pass wall time (list, order, place, "
    "bind, preempt) — the per-pass cost ROADMAP's 100k-pod item needs a "
    "baseline for; observed where the scheduler.sync span closes",
)
replication_ship_latency = REGISTRY.histogram(
    "tpu_operator_replication_ship_latency_seconds",
    "Leader commit-to-majority-ack time per replicated write (the HA "
    "write tax PERF round 8 measured); observed where the replica.ship "
    "span closes",
)
failover_duration = REGISTRY.histogram(
    "tpu_operator_failover_duration_seconds",
    "Campaign-start-to-leadership time of WON replica-set elections "
    "(the 871ms PERF round 8 clocked by hand); observed where the "
    "replica.election span closes",
)
agent_tick_latency = REGISTRY.histogram(
    "tpu_operator_agent_tick_latency_seconds",
    "Node-agent tick (heartbeat + batched pod mirrors, one patch-batch) "
    "round-trip time; observed where the agent.tick span closes",
)
serve_reconcile_latency = REGISTRY.histogram(
    "tpu_operator_serve_reconcile_latency_seconds",
    "TPUServe controller sync wall time per reconcile (the serving "
    "control loop's headline latency); observed where the serve.reconcile "
    "span closes — every controller loop registers its histogram at the "
    "span-close site (oplint OBS002)",
)
serve_ready_latency = REGISTRY.histogram(
    "tpu_operator_serve_ready_latency_seconds",
    "Serving-replica creation-to-ready time (gang create → every member "
    "Running AND ready): THE serving cold-start SLO — the autoscaler's "
    "reaction to a spike is only as good as this plus the decision lag; "
    "observed where the serve.replica_ready span closes",
    # serving readiness spans model-load/warmup territory: sub-second
    # hollow gangs through multi-minute real compile+load
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
             300.0),
)
drain_migration_latency = REGISTRY.histogram(
    "tpu_operator_drain_migration_latency_seconds",
    "Maintenance-drain evacuation time per node (notice adoption → no "
    "live pod bound). A completed drain observes its true latency once; "
    "a drain still in flight past the stuck threshold observes its AGE "
    "every tick — so a stuck drain keeps scoring bad events and the "
    "drain-migration burn-rate objective (controller/slo_defaults.json) "
    "pages instead of staying silent",
    # drains span quick hollow moves through multi-minute checkpoint+
    # reschedule cycles; 60s is the SLO threshold's bucket edge
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 60.0, 120.0, 300.0,
             600.0),
)
autoscaler_sync_latency = REGISTRY.histogram(
    "tpu_operator_autoscaler_sync_latency_seconds",
    "Autoscaler decision-pass wall time (sample every serve, run the "
    "pure recommendation, write changed scales); observed where the "
    "autoscaler.sync span closes",
)
monitor_scrape_latency = REGISTRY.histogram(
    "tpu_operator_monitor_scrape_latency_seconds",
    "Per-target /metrics fetch+parse+ingest time (labeled instance=) — "
    "the monitor's own cost; the slo bench holds its reconcile-p50 tax "
    "to <=2%",
)
monitor_tick_latency = REGISTRY.histogram(
    "tpu_operator_monitor_tick_latency_seconds",
    "One full SLO-monitor pass (scrape every target, evaluate every "
    "objective's burn windows, write alert transitions); observed where "
    "the monitor.sync span closes",
)
