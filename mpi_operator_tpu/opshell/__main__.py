"""Operator main: flags → election → controller + executor + ops endpoints.

≙ /root/reference/v2/cmd/mpi-operator/ (main.go + app/server.go + options):
parse flags, start /healthz+/metrics, run leader election, and reconcile as
leader. ``--store memory`` keeps everything in-process; ``--store
sqlite:/path/db`` backs the store with a shared sqlite file, so multiple
operator replicas (and the tpujob CLI/client) share one apiserver-equivalent
and leader election elects exactly one active reconciler across processes.
`--executor local` additionally runs pods as OS processes.

  python -m mpi_operator_tpu.opshell --store sqlite:/var/lib/tpujob/store.db \\
      --executor local --monitoring-port 8080
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from mpi_operator_tpu.controller.controller import ControllerOptions, TPUJobController
from mpi_operator_tpu.controller.node_monitor import NodeMonitor
from mpi_operator_tpu.executor import LocalExecutor
from mpi_operator_tpu.machinery.cache import InformerCache
from mpi_operator_tpu.machinery.events import EventRecorder
from mpi_operator_tpu.machinery.store import ObjectStore
from mpi_operator_tpu.opshell.election import ElectionConfig, LeaderElector
from mpi_operator_tpu.opshell.server import OpsServer
from mpi_operator_tpu.scheduler import GangScheduler, SliceInventory


def build_parser() -> argparse.ArgumentParser:
    # flag surface ≙ options.go:46-74
    ap = argparse.ArgumentParser(prog="tpu-operator", description=__doc__)
    ap.add_argument("--namespace", default=None,
                    help="watch one namespace (default: all)")
    ap.add_argument("--threadiness", type=int, default=2)
    ap.add_argument("--monitoring-port", type=int, default=8080)
    ap.add_argument("--lock-namespace", default="kube-system")
    ap.add_argument("--no-gang-scheduling", action="store_true")
    ap.add_argument("--no-informer-cache", action="store_true",
                    help="read the store directly instead of the "
                         "watch-fed informer cache (debugging escape "
                         "hatch; the cache is what keeps store read "
                         "load O(1) in cluster size)")
    ap.add_argument("--executor", choices=["none", "local"], default="none",
                    help="'local' runs worker pods as OS processes")
    ap.add_argument("--logs-dir", default=None,
                    help="directory for pod stdout/stderr files (default: a "
                         "temp dir; paths land in pod.status for `ctl logs`)")
    ap.add_argument("--coordinator-port", type=int, default=8476)
    ap.add_argument("--inventory-chips", type=int, default=None,
                    help="finite chip inventory for gang admission "
                         "(default: unbounded)")
    ap.add_argument("--inventory-slices", default=None,
                    help="topology-aware inventory: comma-separated host "
                         "meshes, one per physical slice (e.g. '4x4,4x4'); "
                         "gangs admit only into contiguous free blocks")
    ap.add_argument("--store", default="memory",
                    help="'memory' (in-process), 'sqlite:PATH' (shared "
                         "across processes on one node), or 'http://HOST:PORT' "
                         "(a store server — shared across nodes)")
    ap.add_argument("--serve-store", default=None, metavar="HOST:PORT",
                    help="additionally serve this operator's backing store "
                         "over HTTP so other nodes can use --store http://...")
    ap.add_argument("--token-file", default=None,
                    help="ADMIN bearer token file: required from peers when "
                         "serving (--serve-store), presented when connecting "
                         "to a remote --store http://...")
    ap.add_argument("--read-token-file", default=None,
                    help="READ-ONLY bearer token file for --serve-store: "
                         "read/watch requests may present it instead of the "
                         "admin token; mutations with it get 403. Implies "
                         "reads require a token.")
    ap.add_argument("--agent-tokens-file", default=None,
                    help="for --serve-store: file of 'node-name:token' "
                         "lines — per-agent SCOPED credentials (reads + own "
                         "Node + pods bound to its node only)")
    ap.add_argument("--fair-queue", default=None, metavar="SPEC",
                    help="APF-style per-tenant fair queuing for "
                         "--serve-store: 'inflight=16,queue=64,rate=200,"
                         "burst=400' (any subset; rate in req/s per "
                         "tenant). One noisy tenant's list storm can no "
                         "longer starve another tenant's writes or watch "
                         "pump; over-limit requests get 429. Default: "
                         "open admission")
    ap.add_argument("--quota-file", default=None, metavar="PATH",
                    help="namespace quota admission for --serve-store: "
                         'JSON {"namespace": {"max_jobs": N, "max_chips": '
                         'M}}; over-quota TPUJob creates get a typed 403 '
                         "QuotaExceeded")
    ap.add_argument("--tls-cert", default=None,
                    help="serve --serve-store over TLS with this certificate "
                         "(PEM; ≙ kube-apiserver's TLS on the same seam)")
    ap.add_argument("--tls-key", default=None,
                    help="private key for --tls-cert (PEM; omit when the "
                         "cert file bundles the key)")
    ap.add_argument("--tls-ca-file", default=None,
                    help="CA bundle (or the self-signed cert itself) to "
                         "verify a remote --store https://... against; "
                         "default: system trust store")
    ap.add_argument("--require-nodes", choices=["auto", "always", "never"],
                    default="auto",
                    help="bind gangs only to registered node agents, never "
                         "the in-process 'local' sentinel. 'auto' (default) "
                         "enables this when --executor none and no "
                         "--inventory-slices: that shape IS the cluster "
                         "deployment, and a gang bound to 'local' before the "
                         "first agent registers would wedge forever")
    ap.add_argument("--node-grace", type=float, default=6.0,
                    help="seconds without a node-agent heartbeat before its "
                         "pods are evicted (the node-controller grace)")
    ap.add_argument("--preemption-grace", type=float, default=None,
                    metavar="SECONDS",
                    help="opt-in priority preemption: when the "
                         "capacity-blocked head of the queue outranks a "
                         "running gang and has waited this long, the "
                         "minimal set of lowest-priority running gangs is "
                         "evicted (whole-gang, checkpoint-resumable) to "
                         "make room. Default: disabled")
    ap.add_argument("--lease-duration", type=float, default=15.0,
                    help="leader lease duration in seconds (≙ the reference's "
                         "15s; lower it only for failover testing)")
    ap.add_argument("--renew-deadline", type=float, default=10.0,
                    help="seconds without a successful lease renew before "
                         "this replica stops leading")
    ap.add_argument("--retry-period", type=float, default=5.0,
                    help="seconds between lease acquire/renew attempts")
    ap.add_argument("--event-ttl", type=float, default=3600.0,
                    help="prune Events older than this many seconds "
                         "(the controller's housekeeping sweep, ≙ the "
                         "apiserver's 1h event TTL); 0 disables and keeps "
                         "the audit trail forever")
    ap.add_argument("--chaos-script", default=None, metavar="PATH",
                    help="fault-injection timeline (machinery/chaos.py "
                         "format) armed when this replica becomes leader; "
                         "'kill'/'term' actions on target 'self' crash this "
                         "process at a deterministic offset into its reign — "
                         "the scripted half of the crash-recovery e2e suite")
    ap.add_argument("--no-drain-controller", action="store_true",
                    help="disable the disruption plane's DrainController "
                         "(maintenance-notice drains run leader-only by "
                         "default; with it off, `ctl drain` notices are "
                         "inert and only --now drains work)")
    ap.add_argument("--no-rescheduler", action="store_true",
                    help="disable the goodput-aware defragmenting "
                         "rescheduler (proactive straggler moves + "
                         "make-room defrag drains run leader-only by "
                         "default; the fragmentation gauges go dark "
                         "with it off — the soak bench's A/B arm)")
    ap.add_argument("--reschedule-interval", type=float, default=2.0,
                    help="seconds between rescheduler passes "
                         "(fragmentation gauges + governed moves)")
    ap.add_argument("--reschedule-max-moves", type=int, default=2,
                    help="rescheduler migration budget: at most this "
                         "many gang moves per --reschedule-window "
                         "(the brake on migration storms)")
    ap.add_argument("--reschedule-window", type=float, default=60.0,
                    help="seconds over which --reschedule-max-moves "
                         "is counted (sliding window)")
    ap.add_argument("--no-serving", action="store_true",
                    help="disable the TPUServe controller + autoscaler "
                         "(batch-only operator; the serving workload "
                         "class is on by default)")
    ap.add_argument("--autoscale-interval", type=float, default=2.0,
                    help="seconds between serve-autoscaler decision "
                         "passes (sample pod serve_stats → recommend → "
                         "write spec.replicas)")
    ap.add_argument("--no-goodput", action="store_true",
                    help="disable the workload telemetry plane's goodput "
                         "aggregator (per-job goodput/stall/straggler "
                         "rollups run leader-only by default)")
    ap.add_argument("--goodput-interval", type=float, default=2.0,
                    help="seconds between goodput-aggregator rollup "
                         "passes over running jobs' train_stats")
    ap.add_argument("--no-slo-monitor", action="store_true",
                    help="disable the SLO burn-rate monitor (the alerting "
                         "plane runs leader-only by default, scraping this "
                         "process's own registry plus --scrape-targets)")
    ap.add_argument("--slo-config", default=None, metavar="PATH",
                    help="SLO objectives file (default: $TPUJOB_SLO_CONFIG "
                         "or the packaged slo_defaults.json); the loader "
                         "FAILS CLOSED on unknown metrics/bad thresholds/"
                         "malformed windows — a typo'd objective refuses "
                         "to start rather than silently watching nothing")
    ap.add_argument("--scrape-targets", default="", metavar="MAP",
                    help="extra /metrics endpoints the SLO monitor pulls, "
                         "'name=http://host:port/metrics' comma list "
                         "(store replicas, hollow fleets — anything with "
                         "--monitoring-port); this process is always "
                         "scraped as instance 'operator'")
    ap.add_argument("--scrape-interval", type=float, default=15.0,
                    help="seconds between SLO monitor scrape+evaluate "
                         "passes")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    ap.add_argument("--version", action="store_true",
                    help="print version/build info and exit")
    return ap


def build_store(spec: str, token: str = None, ca_file: str = None):
    if spec == "memory":
        return ObjectStore()
    if spec.startswith("sqlite:"):
        from mpi_operator_tpu.machinery.sqlite_store import SqliteStore

        return SqliteStore(spec[len("sqlite:"):])
    if spec.startswith("http://") or spec.startswith("https://"):
        from mpi_operator_tpu.machinery.http_store import HttpStoreClient

        return HttpStoreClient(spec, token=token, ca_file=ca_file)
    raise SystemExit(f"error: unknown --store {spec!r}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        from mpi_operator_tpu.version import version_string

        print(version_string())
        return 0
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    from mpi_operator_tpu.machinery import trace
    from mpi_operator_tpu.machinery.http_store import (
        read_agent_tokens_file,
        read_token_file,
    )

    # tracing rides TPUJOB_TRACE_DIR (off otherwise; ~zero cost when off)
    trace.configure_from_env("operator")

    try:
        token = read_token_file(args.token_file)
        read_token = read_token_file(args.read_token_file)
        agent_tokens = read_agent_tokens_file(args.agent_tokens_file)
    except (OSError, ValueError) as e:
        print(f"error: token file: {e}", file=sys.stderr)
        return 2
    if (read_token is not None or agent_tokens) and token is None:
        print("error: --read-token-file/--agent-tokens-file require "
              "--token-file (the admin tier anchors auth)", file=sys.stderr)
        return 2
    if args.tls_key and not args.tls_cert:
        print("error: --tls-key requires --tls-cert", file=sys.stderr)
        return 2
    store = build_store(args.store, token=token, ca_file=args.tls_ca_file)
    store_server = None
    if args.serve_store:
        from mpi_operator_tpu.machinery.http_store import (
            HttpStoreClient,
            StoreServer,
            parse_listen,
        )

        if isinstance(store, HttpStoreClient):
            print("error: --serve-store cannot re-serve a remote --store http://",
                  file=sys.stderr)
            return 2
        try:
            host, port = parse_listen(args.serve_store)
        except ValueError as e:
            print(f"error: --serve-store: {e}", file=sys.stderr)
            return 2
        from mpi_operator_tpu.machinery.fairqueue import (
            load_quota_file,
            parse_fair_queue,
        )

        try:
            fairness = parse_fair_queue(args.fair_queue)
            quota = load_quota_file(args.quota_file)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        store_server = StoreServer(
            store, host, port, token=token, read_token=read_token,
            agent_tokens=agent_tokens,
            # a read tier with open reads would be meaningless (see the
            # standalone tpu-store entry point, which does the same)
            auth_reads=read_token is not None,
            tls_cert=args.tls_cert, tls_key=args.tls_key,
            fairness=fairness, quota=quota,
        ).start()
        logging.info("store serving on %s", store_server.url)
    recorder = EventRecorder(store)
    # ONE shared informer cache feeds every control-plane reader (≙ the
    # SharedInformerFactory of the reference): controller, gang scheduler
    # and node monitor all read local watch-fed listers; only writes and a
    # single watch long-poll hit the store — the difference between O(1)
    # and O(jobs × pods × resyncs) store load (opt out: --no-informer-cache)
    cache = None if args.no_informer_cache else InformerCache(store)
    controller = TPUJobController(
        store,
        recorder,
        ControllerOptions(
            namespace=args.namespace,
            threadiness=args.threadiness,
            coordinator_port=args.coordinator_port,
            gang_scheduling=not args.no_gang_scheduling,
            event_ttl=args.event_ttl if args.event_ttl > 0 else None,
        ),
        cache=cache,
    )
    gang = not args.no_gang_scheduling
    if args.inventory_chips is not None and not gang:
        print(
            "error: --inventory-chips requires gang scheduling "
            "(remove --no-gang-scheduling)",
            file=sys.stderr,
        )
        return 2
    if args.inventory_slices is not None and not gang:
        print(
            "error: --inventory-slices requires gang scheduling "
            "(remove --no-gang-scheduling)",
            file=sys.stderr,
        )
        return 2
    if args.inventory_slices is not None and args.inventory_chips is not None:
        print(
            "error: --inventory-chips and --inventory-slices are exclusive "
            "(the topology inventory defines capacity)",
            file=sys.stderr,
        )
        return 2
    try:
        inventory = (
            SliceInventory.parse(args.inventory_slices)
            if args.inventory_slices is not None
            else None
        )
    except ValueError as e:
        print(f"error: --inventory-slices: {e}", file=sys.stderr)
        return 2
    if args.require_nodes == "always" and args.executor == "local":
        # the in-process executor launches 'local'-bound pods; the heal
        # loop would race it unbinding the same pods — a pod could run
        # locally AND be re-placed onto a node (double execution)
        print(
            "error: --require-nodes always conflicts with --executor local "
            "(the local executor runs the 'local'-bound pods the flag "
            "forbids); use --executor none with node agents",
            file=sys.stderr,
        )
        return 2
    if args.require_nodes == "always" and inventory is not None:
        # the require_nodes machinery is scalar-mode only: in topology mode
        # binding targets are already inventory host names that agents claim
        # — accepting 'always' here would be a silent no-op
        print(
            "error: --require-nodes always applies to scalar node mode only "
            "(topology mode binds to inventory hosts, which agents claim "
            "directly); drop the flag or the --inventory-slices",
            file=sys.stderr,
        )
        return 2
    require_nodes = args.require_nodes == "always" or (
        args.require_nodes == "auto"
        and args.executor == "none"
        and inventory is None
    )
    if args.preemption_grace is not None and not gang:
        print(
            "error: --preemption-grace requires gang scheduling "
            "(remove --no-gang-scheduling)",
            file=sys.stderr,
        )
        return 2
    scheduler = (
        GangScheduler(
            store, recorder, chips=args.inventory_chips, inventory=inventory,
            node_grace=args.node_grace, require_nodes=require_nodes,
            preemption_grace=args.preemption_grace, cache=cache,
        )
        if gang
        else None
    )
    executor = (
        LocalExecutor(store, require_binding=gang, logs_dir=args.logs_dir)
        if args.executor == "local"
        else None
    )
    # the node-controller role (leader-only): evicts pods off nodes whose
    # agents stop heartbeating, so gang restarts land on live nodes
    monitor = NodeMonitor(store, recorder, grace=args.node_grace, cache=cache,
                          defer_to_drain=not args.no_drain_controller)

    # the disruption plane (leader-only): adopts maintenance notices and
    # orchestrates budgeted per-node evacuation — batch gangs checkpoint-
    # then-migrate free, serve replicas migrate surge-first, deadline
    # overruns hard-evict (controller/disruption.py)
    drain_controller = None
    if not args.no_drain_controller:
        from mpi_operator_tpu.controller.disruption import DrainController

        drain_controller = DrainController(
            store, recorder, node_grace=args.node_grace, cache=cache,
        )

    # the rescheduler (leader-only, ISSUE 18): proactive migration —
    # straggler moves off sick hardware and make-room defrag drains,
    # governed by migration caps/hysteresis; rides the drain plane's
    # free checkpoint-then-migrate seam (controller/rescheduler.py)
    # defrag drains are executed by the DrainController, so the
    # rescheduler follows it off: a stamp nothing evacuates would just
    # cordon capacity forever
    rescheduler = None
    if not args.no_rescheduler and gang and not args.no_drain_controller:
        from mpi_operator_tpu.controller.rescheduler import Rescheduler

        rescheduler = Rescheduler(
            store, recorder, interval=args.reschedule_interval,
            node_grace=args.node_grace, cache=cache,
            max_moves=args.reschedule_max_moves,
            window_s=args.reschedule_window,
        )

    # the serving workload class (leader-only, like every reconciler):
    # the TPUServe controller drives replica gangs + rollouts, the
    # autoscaler writes their spec.replicas from observed load
    serve_controller = None
    autoscaler = None
    if not args.no_serving:
        from mpi_operator_tpu.controller.autoscaler import ServeAutoscaler
        from mpi_operator_tpu.controller.serve import (
            ServeControllerOptions,
            TPUServeController,
        )

        serve_controller = TPUServeController(
            store, recorder,
            ServeControllerOptions(namespace=args.namespace),
            cache=cache,
        )
        autoscaler = ServeAutoscaler(
            store, recorder, cache=cache, namespace=args.namespace,
            interval=args.autoscale_interval,
        )

    # the workload telemetry plane (leader-only, ISSUE 15): roll pod
    # train_stats up into per-job goodput / stall attribution /
    # straggler detection — the gauges the goodput-collapse objective
    # burns on and the telemetry `ctl top --jobs` renders
    goodput_aggregator = None
    if not args.no_goodput:
        from mpi_operator_tpu.controller.goodput import GoodputAggregator

        goodput_aggregator = GoodputAggregator(
            store, recorder, cache=cache, namespace=args.namespace,
            interval=args.goodput_interval,
        )

    # the SLO plane (leader-only, like every reconciler): scrape the
    # fleet's /metrics, evaluate burn-rate objectives, write Alert
    # objects + incident bundles. Built BEFORE the election so a bad
    # config fails the process at startup, not at leadership.
    slo_monitor = None
    if not args.no_slo_monitor:
        from mpi_operator_tpu.controller.slo_monitor import (
            SLOConfigError,
            build_monitor,
        )
        from mpi_operator_tpu.machinery.telemetry import ScrapeTarget

        try:
            slo_monitor = build_monitor(
                store, scrape_targets=args.scrape_targets,
                slo_config=args.slo_config,
                interval=args.scrape_interval,
                extra_targets=[ScrapeTarget("operator", "self")],
            )
        except (SLOConfigError, ValueError) as e:
            print(f"error: --slo-config/--scrape-targets: {e}",
                  file=sys.stderr)
            return 2

    chaos_script = None
    if args.chaos_script:
        from mpi_operator_tpu.machinery.chaos import (
            ChaosScript,
            ChaosScriptError,
        )

        try:
            chaos_script = ChaosScript.load(args.chaos_script)
        except (OSError, ChaosScriptError) as e:
            # fail fast: a typo'd script silently injecting nothing would
            # make a "passing" chaos run meaningless
            print(f"error: --chaos-script: {e}", file=sys.stderr)
            return 2
        # satisfiability, same fail-fast contract: the operator arms the
        # script with ONE target ('self') and no proxy, so any other
        # fault would be skipped at fire time and the run would claim
        # chaos it never injected (proxy faults and multi-process targets
        # belong to a driving harness, e.g. tests/test_chaos.py)
        unusable = [
            a for a in chaos_script.actions
            if a.fault not in ("kill", "term") or a.target != "self"
        ]
        if unusable:
            bad = unusable[0]
            print(
                f"error: --chaos-script: fault {bad.fault!r} "
                f"target={bad.target or '<none>'!r} is not executable by "
                f"the operator (only kill/term on target 'self' are)",
                file=sys.stderr,
            )
            return 2

    stop = threading.Event()

    def on_started():
        if cache is not None:
            cache.start()
        controller.run()
        if serve_controller is not None:
            serve_controller.run()
        if autoscaler is not None:
            autoscaler.start()
        if scheduler:
            scheduler.start()
        if executor:
            executor.start()
        monitor.start()
        if drain_controller is not None:
            drain_controller.start()
        if rescheduler is not None:
            rescheduler.start()
        if goodput_aggregator is not None:
            goodput_aggregator.start()
        if slo_monitor is not None:
            slo_monitor.start()
        if chaos_script is not None:
            # armed at leadership, not at process start: "kill the leader
            # N seconds into its reign" is then a deterministic, scripted
            # event — the only clock a failover scenario can anchor on
            from mpi_operator_tpu.machinery.chaos import (
                ChaosController,
                SelfTarget,
            )

            logging.warning("chaos script armed (leader reign t=0)")
            ChaosController(
                chaos_script, targets={"self": SelfTarget()}
            ).arm()

    def on_stopped():
        # ≙ OnStoppedLeading → fatal (server.go:246-249): losing the lease
        # stops reconciling immediately
        controller.stop()
        if slo_monitor is not None:
            slo_monitor.stop()
        if goodput_aggregator is not None:
            goodput_aggregator.stop()
        if autoscaler is not None:
            autoscaler.stop()
        if serve_controller is not None:
            serve_controller.stop()
        if scheduler:
            scheduler.stop()
        if executor:
            executor.stop()
        monitor.stop()
        if drain_controller is not None:
            drain_controller.stop()
        if rescheduler is not None:
            rescheduler.stop()
        if cache is not None:
            cache.stop()
        stop.set()

    elector = LeaderElector(
        store,
        config=ElectionConfig(
            namespace=args.lock_namespace,
            lease_duration=args.lease_duration,
            renew_deadline=args.renew_deadline,
            retry_period=args.retry_period,
        ),
        on_started=on_started,
        on_stopped=on_stopped,
    )
    ops = OpsServer(args.monitoring_port, healthy=lambda: True)
    ops.start()

    def on_signal(sig, frame):
        elector.stop()
        elector.release()
        on_stopped()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)

    t = threading.Thread(target=elector.run, daemon=True)
    t.start()
    stop.wait()
    if store_server is not None:
        store_server.stop()
    ops.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
