"""Health + metrics HTTP endpoints.

≙ the reference's /healthz on the monitoring port wired to the leader-
election adaptor plus promhttp's /metrics
(v2/cmd/mpi-operator/app/server.go:192-208, README.md:202-215)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from mpi_operator_tpu.opshell import metrics


class OpsServer:
    """Serves /healthz (200 iff healthy(), ≙ the election healthzAdaptor)
    and /metrics (Prometheus text format)."""

    def __init__(
        self,
        port: int = 8080,
        *,
        healthy: Optional[Callable[[], bool]] = None,
        registry: metrics.Registry = metrics.REGISTRY,
    ):
        self.healthy = healthy or (lambda: True)
        registry_ref = registry
        healthy_ref = self.healthy

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    ok = False
                    try:
                        ok = healthy_ref()
                    # oplint: disable=EXC001 — a throwing health predicate
                    # means NOT healthy; the 500 below is the surfacing
                    except Exception:
                        ok = False
                    body = json.dumps({"healthy": ok}).encode()
                    self.send_response(200 if ok else 500)
                    self.send_header("Content-Type", "application/json")
                elif self.path == "/metrics":
                    body = registry_ref.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                else:
                    body = b"not found"
                    self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self.httpd.server_address[1]  # resolved when port=0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="ops-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
